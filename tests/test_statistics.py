import pytest
from prometheus_client import CollectorRegistry

from clearml_serving_tpu.serving.endpoints import EndpointMetricLogging, MetricType
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor
from clearml_serving_tpu.statistics.broker import (
    FileBrokerConsumer,
    FileBrokerProducer,
    make_consumer,
    make_producer,
)
from clearml_serving_tpu.statistics.metrics import StatisticsController


def test_file_broker_roundtrip(tmp_path):
    producer = FileBrokerProducer(str(tmp_path / "b"))
    consumer = FileBrokerConsumer(str(tmp_path / "b"))
    producer.send_batch([{"_url": "e", "_latency": 0.1}, {"_url": "e", "_count": 2}])
    out = consumer.poll()
    assert len(out) == 2
    # offsets: re-poll returns nothing new
    assert consumer.poll() == []
    producer.send_batch([{"_url": "e2"}])
    assert len(consumer.poll()) == 1


def test_broker_url_scheme(tmp_path):
    assert make_producer("") is None
    assert make_consumer("") is None
    p = make_producer("file://{}".format(tmp_path / "x"))
    c = make_consumer("file://{}".format(tmp_path / "x"))
    p.send_batch([{"_url": "a"}])
    assert c.poll() == [{"_url": "a"}]


def _get_sample(registry, name, suffix="", labels=None):
    value = registry.get_sample_value(name + suffix, labels or {})
    return value


def test_statistics_controller(tmp_path, state_root):
    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="s")
    mrp.add_metric_logging(
        EndpointMetricLogging(
            endpoint="ep1",
            metrics={
                "x0": MetricType(type="scalar", buckets=[0, 1, 2, 3]),
                "label": MetricType(type="enum", buckets=["cat", "dog"]),
                "conf": MetricType(type="value"),
                "hits": MetricType(type="counter"),
            },
        )
    )
    mrp.serialize()

    registry = CollectorRegistry()
    ctl = StatisticsController("file://{}".format(tmp_path / "b"), processor=mrp, registry=registry)
    ctl.sync_specs()
    n = ctl.process_batch(
        [
            {"_url": "ep1", "_latency": 0.05, "_count": 10, "x0": 1.5,
             "label": "cat", "conf": 0.9, "hits": 3},
            {"_url": "ep1", "_latency": 0.2, "_count": 10, "x0": [0.5, 2.5],
             "label": "dog", "conf": 0.4, "hits": 2},
        ]
    )
    assert n == 2
    assert _get_sample(registry, "ep1__latency", "_count") == 2.0
    assert _get_sample(registry, "ep1__count", "_total") == 20.0
    assert _get_sample(registry, "ep1_x0", "_count") == 3.0  # list observed per-value
    # declared-bucket enum -> reference-parity EnumHistogram export shape
    assert _get_sample(registry, "ep1_label", "_bucket", {"enum": "cat"}) == 1.0
    assert _get_sample(registry, "ep1_label", "_bucket", {"enum": "dog"}) == 1.0
    assert _get_sample(registry, "ep1_label", "_sum") == 2.0
    assert _get_sample(registry, "ep1_conf") == 0.4  # gauge keeps last
    assert _get_sample(registry, "ep1_hits", "_total") == 5.0


def test_enum_histogram_semantics(tmp_path, state_root):
    """Declared buckets fix the exported set and ordering (reference
    EnumHistogram); undeclared values are dropped; spec-less enums fall
    back to the labeled Counter."""
    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="se")
    mrp.add_metric_logging(
        EndpointMetricLogging(
            endpoint="ep2",
            metrics={
                "cls": MetricType(type="enum", buckets=["a", "b", "c"]),
                # single declared bucket: below EnumHistogram's 2-bucket
                # minimum (matches reference), falls back to labeled Counter
                "free": MetricType(type="enum", buckets=["only"]),
            },
        )
    )
    mrp.serialize()
    registry = CollectorRegistry()
    ctl = StatisticsController(
        "file://{}".format(tmp_path / "b"), processor=mrp, registry=registry
    )
    ctl.sync_specs()
    ctl.process_batch(
        [
            {"_url": "ep2", "cls": "b", "free": "anything"},
            {"_url": "ep2", "cls": ["b", "zzz"], "free": "other"},
        ]
    )
    assert _get_sample(registry, "ep2_cls", "_bucket", {"enum": "a"}) == 0.0
    assert _get_sample(registry, "ep2_cls", "_bucket", {"enum": "b"}) == 2.0
    assert _get_sample(registry, "ep2_cls", "_sum") == 2.0  # "zzz" dropped
    # undeclared value has no series at all (fixed bucket set)
    assert _get_sample(registry, "ep2_cls", "_bucket", {"enum": "zzz"}) is None
    # sub-minimum bucket list keeps the dynamic labeled-Counter shape
    assert _get_sample(registry, "ep2_free", "_total", {"value": "anything"}) == 1.0
    assert _get_sample(registry, "ep2_free", "_total", {"value": "other"}) == 1.0


def test_unknown_endpoint_reserved_only(tmp_path, state_root):
    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="s2")
    mrp.serialize()
    registry = CollectorRegistry()
    ctl = StatisticsController("file://{}".format(tmp_path / "b"), processor=mrp, registry=registry)
    ctl.sync_specs()
    ctl.process_batch([{"_url": "mystery", "_latency": 0.1, "_count": 1, "custom": 5}])
    assert _get_sample(registry, "mystery__latency", "_count") == 1.0
    # unknown variable without a spec is dropped
    assert _get_sample(registry, "mystery_custom") is None


def test_device_gauges_no_crash(tmp_path):
    registry = CollectorRegistry()
    ctl = StatisticsController("", registry=registry)
    ctl.update_device_gauges()  # CPU backend: must not raise


def test_prefix_cache_collector_exports_live_counters():
    """The radix prefix cache's hit/miss/eviction counters and the page
    pool's sharing/CoW gauges are scraped live (no push path needed)."""
    import numpy as np

    from clearml_serving_tpu.llm.kv_cache import PagePool
    from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache
    from clearml_serving_tpu.statistics.metrics import register_prefix_cache

    pool = PagePool(num_pages=16, page_size=2, max_slots=2)
    cache = RadixPrefixCache(block=4, pool=pool, page_bytes=32)
    registry = CollectorRegistry()
    register_prefix_cache(cache, pool, registry=registry, key="m1")

    ids = [1, 2, 3, 4, 5, 6]
    assert cache.lookup_pages(ids, 0) is None          # miss
    pool.allocate(0, 6)
    cache.store_pages(ids, 0, pool.slot_pages(0))
    hit = cache.lookup_pages(ids, 0)                   # hit (4 tokens)
    cache.release(hit)

    def val(name, key="m1"):
        return registry.get_sample_value(name, {"model": key})

    def hits_val(key="m1", tier="hbm"):
        # the hit counter carries a serving-tier label (docs/kv_tiering.md)
        return registry.get_sample_value(
            "llm_prefix_cache_hits_total", {"model": key, "tier": tier}
        )

    assert hits_val() == 1
    assert val("llm_prefix_cache_misses_total") == 1
    assert val("llm_prefix_cache_hit_tokens_total") == 4
    assert val("llm_prefix_cache_nodes") == 1
    assert val("llm_prefix_cache_pages") == 2
    assert val("llm_prefix_cache_bytes") == 64
    assert val("kv_pool_shared_pages") == 2            # slot + cache refs
    assert val("kv_pool_cow_events_total") == 0
    assert val("kv_pool_free_pages") == pool.free_pages

    # dense-backend registration (no pool) lands on the SAME collector
    # under its own model label; re-registering a key REPLACES the entry
    # (engine hot-reload must not leak the old cache or split series)
    dense = RadixPrefixCache(block=2)
    c2 = register_prefix_cache(dense, registry=registry, key="m2")
    k = np.zeros((1, 1, 4, 1, 2), np.float32)
    dense.store([1, 2, 3], 0, {"k": k, "v": k})
    assert dense.lookup([1, 2, 9], 0) is not None
    assert hits_val("m2") == 1
    assert val("kv_pool_shared_pages", "m2") is None
    assert hits_val("m1") == 1  # m1 intact

    fresh = RadixPrefixCache(block=2)
    c3 = register_prefix_cache(fresh, registry=registry, key="m2")
    assert c3 is c2  # same collector, entry swapped
    assert hits_val("m2") == 0


def test_prefix_cache_collector_skips_stats_less_probes():
    """The process backend's routing-only prefix probe has no ``stats``
    surface (the real cache lives in the worker; its stats come back over
    the health RPC). A registered stats-less entry must not poison the
    whole registry scrape — and real entries keep exporting."""
    from prometheus_client import generate_latest

    from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache
    from clearml_serving_tpu.serving.process_replica import _PrefixProbe
    from clearml_serving_tpu.statistics.metrics import register_prefix_cache

    probe = _PrefixProbe(object(), block=16)
    assert not hasattr(probe, "stats")  # the premise this test pins

    registry = CollectorRegistry()
    cache = RadixPrefixCache(block=2)
    register_prefix_cache(cache, registry=registry, key="real")
    register_prefix_cache(probe, registry=registry, key="worker@r0",
                          model="worker", replica="r0")

    blob = generate_latest(registry).decode()  # must not raise
    assert 'model="real"' in blob
    assert "worker@r0" not in blob


def test_engine_lifecycle_collector_exports_counters_and_gauges():
    """Shed/deadline/watchdog counters and the queue-depth / active-slot
    gauges scrape live from a provider callable (the engine's
    lifecycle_stats, or the gRPC client's retry stats)."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 3,
        "active_slots": 2,
        "ready": 1,
        "sheds": {"queue": 4, "pool": 1},
        "deadlines": {"queue": 2, "ttft": 1, "total": 5},
        "watchdog_trips": 1,
        "step_failures": 2,
    }
    registry = CollectorRegistry()
    collector = register_engine_lifecycle(
        lambda: stats, registry=registry, key="m1"
    )

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    # plain queue_depth ints land under class="all" (legacy providers);
    # per-class series come from a queue_depths dict (see the SLO test)
    assert val("engine_queue_depth", **{"class": "all"}) == 3
    assert val("engine_active_slots") == 2
    assert val("engine_ready") == 1
    assert val("engine_sheds_total", reason="queue", **{"class": "all"}) == 4
    assert val("engine_sheds_total", reason="pool", **{"class": "all"}) == 1
    assert val("engine_deadline_hits_total", stage="ttft") == 1
    assert val("engine_watchdog_trips_total") == 1
    assert val("engine_step_failures_total") == 2

    # gauges move on the next scrape (read live, not pushed)
    stats["queue_depth"] = 7
    assert val("engine_queue_depth", **{"class": "all"}) == 7

    # the gRPC client's retry stats ride the same collector
    from clearml_serving_tpu.engines.grpc_client import grpc_lifecycle_stats

    c2 = register_engine_lifecycle(
        grpc_lifecycle_stats, registry=registry, key="grpc"
    )
    assert c2 is collector
    assert registry.get_sample_value(
        "grpc_client_upstream_total", {"model": "grpc", "kind": "retries"}
    ) is not None

    # re-registering a key replaces the provider (hot-reload semantics)
    register_engine_lifecycle(
        lambda: {"queue_depth": 0, "active_slots": 0}, registry=registry,
        key="m1",
    )
    assert val("engine_queue_depth", **{"class": "all"}) == 0


def test_engine_pipeline_metrics_exported():
    """Pipelined-decode observability (docs/pipelined_decode.md): the
    lifecycle collector exports the in-flight gauge, the configured depth,
    and the dispatch/retire stage histograms from the provider's
    ``pipeline`` block — cumulative Prometheus buckets built from the
    engine's fixed-bucket snapshots."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    snap = {
        "buckets": [1.0, 2.5, 5.0],
        "counts": [2, 1, 0, 3],  # last bucket = +Inf overflow
        "sum_ms": 40.0,
        "count": 6,
    }
    stats = {
        "queue_depth": 0,
        "active_slots": 1,
        "ready": 1,
        "pipeline": {
            "depth": 2,
            "inflight": 1,
            "dispatch_ms": snap,
            "retire_ms": {"buckets": [1.0], "counts": [5, 0],
                          "sum_ms": 2.5, "count": 5},
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("engine_pipeline_inflight") == 1
    assert val("engine_pipeline_depth") == 2
    # cumulative histogram semantics: le buckets accumulate, +Inf = count
    assert val("engine_step_dispatch_ms_bucket", le="1.0") == 2
    assert val("engine_step_dispatch_ms_bucket", le="2.5") == 3
    assert val("engine_step_dispatch_ms_bucket", le="5.0") == 3
    assert val("engine_step_dispatch_ms_bucket", le="+Inf") == 6
    assert val("engine_step_dispatch_ms_sum") == 40.0
    assert val("engine_step_retire_ms_bucket", le="+Inf") == 5
    assert val("engine_step_retire_ms_sum") == 2.5
    # the in-flight gauge reads live on every scrape
    stats["pipeline"]["inflight"] = 0
    assert val("engine_pipeline_inflight") == 0
    # providers without a pipeline block keep the historical families only
    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {"queue_depth": 1}, registry=registry2, key="m2"
    )
    assert registry2.get_sample_value(
        "engine_pipeline_inflight", {"model": "m2"}
    ) is None


def test_engine_sharding_metrics_exported():
    """Sharding-discipline observability (docs/static_analysis.md TPU8xx):
    the lifecycle collector exports the sentry's audit counter and the two
    violation classes from the provider's ``sharding`` block; providers
    without the block (sentry unarmed) keep the historical families only."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 0,
        "sharding": {
            "mode": "audit",
            "strict": False,
            "audits": 12,
            "arrays_checked": 57,
            "implicit_transfers": 1,
            "unplanned_reshards": 0,
            "declared_paths": 25,
            "violations": 0,
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("engine_shard_audits_total") == 12
    assert val("engine_shard_violations_total",
               kind="implicit_transfer") == 1
    assert val("engine_shard_violations_total",
               kind="unplanned_reshard") == 0

    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {"queue_depth": 1, "sharding": None},
        registry=registry2, key="m2",
    )
    assert registry2.get_sample_value(
        "engine_shard_audits_total", {"model": "m2"}
    ) is None


def test_engine_slo_metrics_exported():
    """SLO-scheduling observability (docs/slo_scheduling.md): per-class
    queue depths, per-(reason, class) sheds, the preemption counter and the
    brownout stage/score gauges — from a synthetic provider AND end to end
    against a real engine's lifecycle_stats()."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 5,
        "queue_depths": {"interactive": 3, "batch": 2, "best_effort": 0},
        "sheds": {"queue": 3, "pool": 0},
        "sheds_by_class": {
            "queue": {"best_effort": 2, "batch": 1},
            "brownout": {"best_effort": 4},
        },
        "preemptions": 6,
        "brownout": {"stage": 2, "score": 0.91, "signals": {"queue": 0.91}},
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("engine_queue_depth", **{"class": "interactive"}) == 3
    assert val("engine_queue_depth", **{"class": "batch"}) == 2
    assert val("engine_queue_depth", **{"class": "all"}) == 5
    assert val("engine_sheds_total", reason="queue",
               **{"class": "best_effort"}) == 2
    assert val("engine_sheds_total", reason="brownout",
               **{"class": "best_effort"}) == 4
    assert val("engine_preemptions_total") == 6
    assert val("engine_brownout_stage") == 2
    assert val("engine_brownout_score") == 0.91
    # the stage gauge reads live on the next scrape
    stats["brownout"]["stage"] = 0
    assert val("engine_brownout_stage") == 0

    # providers without the SLO block keep the historical families only
    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {"queue_depth": 1}, registry=registry2, key="m2"
    )
    assert registry2.get_sample_value(
        "engine_preemptions_total", {"model": "m2"}
    ) is None

    # end to end against a REAL engine with admission control (brownout
    # enabled by default when max_pending is set)
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.errors import EngineOverloadedError
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16], eos_token_id=None, max_pending=1,
        # brownout OFF: this test exercises the QUEUE-full class shed —
        # with the controller live, a full 1-deep queue scores 1.0 and
        # whether C sheds under reason="queue" or reason="brownout"
        # depends on the controller's 0.1 s refresh throttle (an observed
        # under-load flake); brownout shedding has its own tests
        brownout=False,
    )
    try:
        registry3 = CollectorRegistry()
        register_engine_lifecycle(
            engine.lifecycle_stats, registry=registry3, key="llm"
        )

        async def run():
            a = GenRequest(prompt_ids=[1, 2], max_new_tokens=10_000)
            agen = engine.generate(a)
            await agen.__anext__()  # A holds a slot
            # A2 holds the OTHER slot (max_batch=2): without it, the loop
            # can admit B between the queue-depth check below and C's
            # arrival, and C then queues instead of shedding (observed as
            # a rare under-load flake)
            a2 = GenRequest(prompt_ids=[1, 5], max_new_tokens=10_000)
            agen2 = engine.generate(a2)
            await agen2.__anext__()
            b = GenRequest(
                prompt_ids=[1, 3], max_new_tokens=2, priority="batch"
            )
            b_task = asyncio.create_task(
                engine.generate(b).__anext__()
            )
            while engine._pending.qsize() < 1:
                await asyncio.sleep(0.005)
            # queue at the bound: a best_effort arrival sheds
            c = GenRequest(
                prompt_ids=[1, 4], max_new_tokens=2, priority="best_effort"
            )
            try:
                async for _ in engine.generate(c):
                    pass
            except EngineOverloadedError:
                pass
            b_task.cancel()
            try:
                await b_task
            except (asyncio.CancelledError, Exception):
                pass
            await agen.aclose()
            await agen2.aclose()

        asyncio.run(run())

        def rval(name, **labels):
            return registry3.get_sample_value(name, {"model": "llm", **labels})

        # per-class depths export live (batch request parked or drained by
        # now — the family exists with all three classes)
        for cls in ("interactive", "batch", "best_effort"):
            assert rval("engine_queue_depth", **{"class": cls}) is not None
        assert rval(
            "engine_sheds_total", reason="queue", **{"class": "best_effort"}
        ) == 1
        assert rval("engine_preemptions_total") == 0
        # brownout disabled on this engine (determinism note above): the
        # stage gauge must be absent, not zero — the synthetic provider
        # half of this test covers the live-gauge path
        assert rval("engine_brownout_stage") is None
    finally:
        engine.stop()


def test_engine_kv_pool_metrics_exported():
    """Paged-pool capacity observability (docs/paged_kv_quant.md): the
    lifecycle collector exports engine_kv_pool_bytes{kind=kv|scale} and the
    engine_kv_pool_dtype info gauge from the provider's ``kv_pool`` block —
    the int8 halving must be visible on a dashboard, live against a real
    engine's lifecycle_stats()."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 0,
        "kv_pool": {
            "kv": 1024, "scale": 256, "dtype": "int8",
            "num_pages": 8, "page_size": 16,
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("engine_kv_pool_bytes", kind="kv") == 1024
    assert val("engine_kv_pool_bytes", kind="scale") == 256
    assert val("engine_kv_pool_dtype", dtype="int8") == 1
    # dense-backend providers (kv_pool None) export no pool families
    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {"queue_depth": 1, "kv_pool": None},
        registry=registry2, key="m2",
    )
    assert registry2.get_sample_value(
        "engine_kv_pool_bytes", {"model": "m2", "kind": "kv"}
    ) is None

    # end to end against a REAL int8 paged engine's lifecycle_stats
    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import LLMEngineCore

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32",
                  "kv_quant": "int8"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16], eos_token_id=None, cache_mode="paged",
    )
    try:
        registry3 = CollectorRegistry()
        register_engine_lifecycle(
            engine.lifecycle_stats, registry=registry3, key="llm"
        )
        expect = engine.paged_cache.pool_bytes()
        assert registry3.get_sample_value(
            "engine_kv_pool_bytes", {"model": "llm", "kind": "kv"}
        ) == expect["kv"]
        assert registry3.get_sample_value(
            "engine_kv_pool_bytes", {"model": "llm", "kind": "scale"}
        ) == expect["scale"]
        assert expect["scale"] > 0
        assert registry3.get_sample_value(
            "engine_kv_pool_dtype", {"model": "llm", "dtype": "int8"}
        ) == 1
    finally:
        engine.stop()


def test_engine_ragged_metrics_exported():
    """Ragged-scheduler observability (docs/ragged_attention.md): the
    step-token-budget utilization histogram, per-phase row counters, the
    live job gauge and the effective-budget gauge — from a synthetic
    provider AND end to end against a real ragged engine."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 0,
        "ragged": {
            "step_token_budget": 64,
            "effective_budget": 48,
            "prefill_jobs": 2,
            "steps": 7,
            "budget_utilization": {
                "buckets": [0.1, 0.25, 0.5, 0.75, 0.9, 1.0],
                "counts": [0, 1, 2, 3, 1, 0, 0],
                "sum_ms": 4.25,
                "count": 7,
            },
            "step_rows": {"prefill": 9, "decode": 21, "spec_verify": 4},
            # multi-step / spec-as-row families (ISSUE 13)
            "decode_steps": 4,
            "decode_tokens": 57,
            "tokens_per_launch": {
                "buckets": [1, 2, 4, 8, 16, 32, 64],
                "counts": [1, 1, 3, 2, 0, 0, 0, 0],
                "sum_ms": 57.0,
                "count": 7,
            },
            "spec_acceptance": {
                "buckets": [0.0, 0.2, 0.4, 0.6, 0.8, 1.0],
                "counts": [1, 0, 1, 0, 0, 2, 0],
                "sum_ms": 2.5,
                "count": 4,
            },
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("engine_step_rows_total", phase="prefill") == 9
    assert val("engine_step_rows_total", phase="decode") == 21
    assert val("engine_step_rows_total", phase="spec_verify") == 4
    assert val("engine_ragged_prefill_jobs") == 2
    assert val("engine_step_token_budget") == 48
    # histogram: cumulative buckets + count/sum
    assert registry.get_sample_value(
        "engine_step_token_budget_utilization_bucket",
        {"model": "m1", "le": "0.75"},
    ) == 6
    assert registry.get_sample_value(
        "engine_step_token_budget_utilization_count", {"model": "m1"}
    ) == 7
    # decode tokens per launch: the dispatch-bubble amortization headline
    assert registry.get_sample_value(
        "engine_decode_tokens_per_launch_count", {"model": "m1"}
    ) == 7
    assert registry.get_sample_value(
        "engine_decode_tokens_per_launch_sum", {"model": "m1"}
    ) == 57.0
    assert registry.get_sample_value(
        "engine_decode_tokens_per_launch_bucket", {"model": "m1", "le": "4"}
    ) == 5
    # per-launch spec acceptance fraction
    assert registry.get_sample_value(
        "engine_spec_acceptance_rate_count", {"model": "m1"}
    ) == 4
    assert registry.get_sample_value(
        "engine_spec_acceptance_rate_bucket", {"model": "m1", "le": "0.4"}
    ) == 2

    # providers without the block (legacy scheduler) skip the families
    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {"queue_depth": 1, "ragged": None}, registry=registry2,
        key="m2",
    )
    assert registry2.get_sample_value(
        "engine_ragged_prefill_jobs", {"model": "m2"}
    ) is None

    # end to end: a real ragged engine's lifecycle_stats() feeds the same
    # families after serving one request
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, scheduler="ragged", step_token_budget=8,
        cache_mode="paged", speculation="ngram", spec_k=2, spec_ngram=2,
    )
    try:
        registry3 = CollectorRegistry()
        register_engine_lifecycle(
            engine.lifecycle_stats, registry=registry3, key="llm"
        )

        async def run():
            # a repetitive prompt so the n-gram proposer drafts: decode
            # rides the mixed launches as spec verify rows
            req = GenRequest(prompt_ids=[1, 2, 3, 1, 2, 3], max_new_tokens=6)
            out = [t async for t in engine.generate(req)]
            await engine.wait_drained()
            return out

        out = asyncio.run(run())
        assert len(out) == 6

        def rval(name, **labels):
            return registry3.get_sample_value(name, {"model": "llm", **labels})

        assert rval("engine_step_rows_total", phase="prefill") >= 1
        assert rval("engine_step_rows_total", phase="spec_verify") >= 1
        assert rval("engine_step_token_budget") == 8
        assert rval("engine_ragged_prefill_jobs") == 0
        assert registry3.get_sample_value(
            "engine_step_token_budget_utilization_count", {"model": "llm"}
        ) >= 1
        assert registry3.get_sample_value(
            "engine_decode_tokens_per_launch_count", {"model": "llm"}
        ) >= 1
        assert registry3.get_sample_value(
            "engine_spec_acceptance_rate_count", {"model": "llm"}
        ) >= 1
    finally:
        engine.stop()


def test_engine_kv_tier_metrics_exported():
    """Host-RAM KV tier observability (docs/kv_tiering.md): the lifecycle
    collector exports engine_kv_tier_pages{tier} / engine_kv_tier_bytes
    {tier} gauges and the engine_kv_demotions_total /
    engine_kv_promotions_total counters from the provider's ``kv_tier``
    block; the prefix-cache hit counter carries the serving tier. Checked
    from a synthetic provider AND end to end against a real tiered engine
    that demoted and promoted a run."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 0,
        "kv_tier": {
            "pages": {"hbm": 4, "host": 12},
            "bytes": {"hbm": 1024, "host": 3072},
            "demotions": 9, "promotions": 3,
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("engine_kv_tier_pages", tier="hbm") == 4
    assert val("engine_kv_tier_pages", tier="host") == 12
    assert val("engine_kv_tier_bytes", tier="host") == 3072
    assert val("engine_kv_demotions_total") == 9
    assert val("engine_kv_promotions_total") == 3

    # untiered providers (kv_tier None) export no tier families
    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {"queue_depth": 1, "kv_tier": None}, registry=registry2,
        key="m2",
    )
    assert registry2.get_sample_value(
        "engine_kv_tier_pages", {"model": "m2", "tier": "hbm"}
    ) is None

    # end to end: a real tiered engine after a demote -> promote cycle
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
    from clearml_serving_tpu.statistics.metrics import register_prefix_cache

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32",
                  "kv_quant": "int8"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=96,
        prefill_buckets=[16, 64], eos_token_id=None, cache_mode="paged",
        prefix_cache=64, prefix_block=16, prefix_cache_host_pages=16,
    )
    try:
        registry3 = CollectorRegistry()
        register_engine_lifecycle(
            engine.lifecycle_stats, registry=registry3, key="llm"
        )
        register_prefix_cache(
            engine._prefix, engine.paged_cache.pool, registry=registry3,
            key="llm",
        )
        prompt = [(7 * i + 3) % 100 + 1 for i in range(40)]

        async def run():
            req = GenRequest(prompt_ids=list(prompt), max_new_tokens=3)
            out = [t async for t in engine.generate(req)]
            await engine.wait_drained()
            return out

        asyncio.run(run())
        assert engine._prefix.spill(0) == 2
        asyncio.run(run())  # warm revisit: host-tier hit promotes

        def rval(name, **labels):
            return registry3.get_sample_value(name, {"model": "llm", **labels})

        assert rval("engine_kv_tier_pages", tier="hbm") == 2  # promoted back
        assert rval("engine_kv_tier_pages", tier="host") == 0
        assert rval("engine_kv_tier_bytes", tier="hbm") > 0
        assert rval("engine_kv_demotions_total") == 1  # one batched round
        assert rval("engine_kv_promotions_total") == 1
        # the prefix-cache hit counter carries the serving tier
        assert rval("llm_prefix_cache_hits_total", tier="host") == 1
        assert rval("llm_prefix_cache_hits_total", tier="hbm") == 0
    finally:
        engine.stop()


def test_engine_compile_metrics_exported(monkeypatch):
    """Compile-surface observability (docs/static_analysis.md TPU6xx): the
    lifecycle collector exports engine_xla_compiles_total{phase} and the
    engine_xla_compile_ms histogram from the provider's ``compile`` block —
    from a synthetic provider AND end to end against a real engine with the
    compile sentry armed."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 0,
        "compile": {
            "mode": "log", "strict": False, "fenced": True,
            "warmup": 7, "serve": 2, "violations": 0,
            "compile_ms": {
                "buckets": [10.0, 50.0],
                "counts": [3, 4, 2],
                "sum_ms": 431.0,
            },
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("engine_xla_compiles_total", phase="warmup") == 7
    assert val("engine_xla_compiles_total", phase="serve") == 2
    assert registry.get_sample_value(
        "engine_xla_compile_ms_bucket", {"model": "m1", "le": "50.0"}
    ) == 7  # cumulative: 3 + 4
    assert registry.get_sample_value(
        "engine_xla_compile_ms_sum", {"model": "m1"}
    ) == 431.0
    # unarmed providers (compile None) export no compile families
    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {"queue_depth": 1, "compile": None},
        registry=registry2, key="m2",
    )
    assert registry2.get_sample_value(
        "engine_xla_compiles_total", {"model": "m2", "phase": "warmup"}
    ) is None

    # end to end against a REAL engine with the sentry armed: the engine's
    # lifecycle_stats carries the live sentry block, and a fresh compile in
    # the process bumps the exported warmup counter
    import jax
    import jax.numpy as jnp

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm import compile_sentry
    from clearml_serving_tpu.llm.engine import LLMEngineCore

    monkeypatch.setenv("TPUSERVE_COMPILE_SENTRY", "1")
    sentry = compile_sentry.get()
    sentry.reset(strict=False)
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16], eos_token_id=None,
    )
    try:
        assert engine._compile_sentry is sentry
        registry3 = CollectorRegistry()
        register_engine_lifecycle(
            engine.lifecycle_stats, registry=registry3, key="llm"
        )
        jax.jit(lambda x: x * 17)(jnp.ones((3,)))  # fresh lambda: compiles
        count = registry3.get_sample_value(
            "engine_xla_compiles_total", {"model": "llm", "phase": "warmup"}
        )
        assert count is not None and count >= 1
        assert registry3.get_sample_value(
            "engine_xla_compiles_total", {"model": "llm", "phase": "serve"}
        ) == 0
    finally:
        engine.stop()
        sentry.reset(strict=False)


def test_engine_ledger_metrics_exported(monkeypatch):
    """Ownership-discipline observability (docs/static_analysis.md TPU7xx):
    the lifecycle collector exports engine_ledger_outstanding{resource} and
    engine_ledger_leaks_total from the provider's ``ledger`` block — from a
    synthetic provider AND end to end against a real engine with the
    ownership ledger armed."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 0,
        "ledger": {
            "strict": True, "acquires": 40, "releases": 37,
            "leaks": 2, "double_releases": 1, "violations": 3,
            "outstanding": {"pages.slot": 0, "pages.ref": 3,
                            "prefix.resume_pin": 1},
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("engine_ledger_outstanding", resource="pages.ref") == 3
    assert val("engine_ledger_outstanding", resource="prefix.resume_pin") == 1
    assert val("engine_ledger_outstanding", resource="pages.slot") == 0
    assert val("engine_ledger_leaks_total") == 2
    # unarmed providers (ledger None) export no ledger families
    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {"queue_depth": 1, "ledger": None},
        registry=registry2, key="m2",
    )
    assert registry2.get_sample_value(
        "engine_ledger_leaks_total", {"model": "m2"}
    ) is None

    # end to end against a REAL engine with the ledger armed: the engine's
    # lifecycle_stats carries the live block, and a pool acquire in the
    # process surfaces in the outstanding gauge
    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm import lifecycle_ledger
    from clearml_serving_tpu.llm.engine import LLMEngineCore

    monkeypatch.setenv("TPUSERVE_LEDGER", "1")
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, cache_mode="paged",
        page_size=16, prefill_buckets=[16], eos_token_id=None,
    )
    try:
        assert engine._ledger is not None
        engine._ledger.reset(strict=False)
        registry3 = CollectorRegistry()
        register_engine_lifecycle(
            engine.lifecycle_stats, registry=registry3, key="llm"
        )
        engine.paged_cache.pool.allocate(0, 20)  # 2 pages outstanding
        assert registry3.get_sample_value(
            "engine_ledger_outstanding",
            {"model": "llm", "resource": "pages.slot"},
        ) == 2
        assert registry3.get_sample_value(
            "engine_ledger_leaks_total", {"model": "llm"}
        ) == 0
        engine.paged_cache.pool.free(0)
        assert registry3.get_sample_value(
            "engine_ledger_outstanding",
            {"model": "llm", "resource": "pages.slot"},
        ) == 0
    finally:
        engine.stop()
        lifecycle_ledger.get().reset(strict=False)
        lifecycle_ledger.disarm()


def test_replica_label_on_lifecycle_families():
    """Replica fleets (docs/replication.md): a provider that reports a
    ``replica`` id gets the replica label on ITS samples (two replicas of
    one model would otherwise emit duplicate series and Prometheus
    rejects the scrape), a ``model`` key overrides the entry key so entry
    keys stay unique per replica — and the label shape is PER PROVIDER: a
    fleet registering on a shared registry never changes a legacy
    single-engine endpoint's series identity."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    s0 = {
        "model": "m", "replica": "r0",
        "queue_depth": 2, "active_slots": 1, "ready": 1,
    }
    s1 = {
        "model": "m", "replica": "r1",
        "queue_depth": 5, "active_slots": 0, "ready": 0,
        "sheds": {"queue": 3},
        "watchdog_trips": 1,
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: s0, registry=registry, key="m@r0")
    register_engine_lifecycle(lambda: s1, registry=registry, key="m@r1")
    # a LEGACY endpoint co-hosted on the same registry
    register_engine_lifecycle(
        lambda: {"queue_depth": 1, "ready": 1}, registry=registry,
        key="legacy",
    )

    def val(name, **labels):
        return registry.get_sample_value(name, labels)

    assert val("engine_queue_depth", model="m", replica="r0",
               **{"class": "all"}) == 2
    assert val("engine_queue_depth", model="m", replica="r1",
               **{"class": "all"}) == 5
    assert val("engine_ready", model="m", replica="r0") == 1
    assert val("engine_ready", model="m", replica="r1") == 0
    assert val("engine_sheds_total", model="m", replica="r1",
               reason="queue", **{"class": "all"}) == 3
    assert val("engine_watchdog_trips_total", model="m", replica="r1") == 1
    # the legacy endpoint's series identity is UNTOUCHED by the fleet:
    # dashboards matching {model="legacy"} with no replica label keep
    # working, and nothing flaps when the fleet endpoint is evicted
    assert val("engine_queue_depth", model="legacy",
               **{"class": "all"}) == 1
    assert val("engine_ready", model="legacy") == 1
    # gauges read live on the next scrape
    s0["queue_depth"] = 7
    assert val("engine_queue_depth", model="m", replica="r0",
               **{"class": "all"}) == 7


def test_replica_router_collector_exports_ring_and_routes():
    """router_requests_total{replica,route} + router_ring_size and the
    eject/readmit/fleet-brownout families from a synthetic
    ReplicaRouter.stats() provider (docs/replication.md)."""
    from clearml_serving_tpu.statistics.metrics import register_replica_router

    stats = {
        "replicas": 2,
        "ring_size": 1,
        "requests": {
            "r0": {"affine": 5, "spill": 1, "rebalance": 2},
            "r1": {"affine": 3, "spill": 0, "rebalance": 0},
        },
        "ejections": {"r0": 0, "r1": 1},
        "readmissions": {"r0": 0, "r1": 1},
        "fleet_sheds": {"best_effort": 4},
        "fleet_brownout": {"stage": 2, "stages": {"r0": 2, "r1": 3}},
    }
    registry = CollectorRegistry()
    register_replica_router(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("router_ring_size") == 1
    assert val("router_replicas") == 2
    # providers without a roles map default every member to role="hybrid"
    assert val("router_requests_total", replica="r0", route="affine",
               role="hybrid") == 5
    assert val("router_requests_total", replica="r0", route="spill",
               role="hybrid") == 1
    assert val("router_requests_total", replica="r1", route="rebalance",
               role="hybrid") == 0
    assert val("router_ejections_total", replica="r1", role="hybrid") == 1
    assert val("router_readmissions_total", replica="r1",
               role="hybrid") == 1
    assert val("router_fleet_brownout_stage") == 2
    assert val("router_fleet_sheds_total", **{"class": "best_effort"}) == 4
    # the ring gauge reads live on the next scrape
    stats["ring_size"] = 2
    assert val("router_ring_size") == 2


def test_replica_router_role_label_and_role_members():
    """Role-split fleets (docs/disaggregation.md): the per-replica
    router families carry the replica's role, and router_role_members
    gauges the ring composition by role."""
    from clearml_serving_tpu.statistics.metrics import register_replica_router

    stats = {
        "replicas": 2,
        "ring_size": 2,
        "ring": ["r0", "r1"],
        "roles": {"r0": "prefill", "r1": "decode"},
        "requests": {
            "r0": {"affine": 1, "spill": 0, "rebalance": 0},
            "r1": {"affine": 7, "spill": 0, "rebalance": 1},
        },
        "ejections": {"r0": 2, "r1": 0},
        "readmissions": {"r0": 2, "r1": 0},
        "fleet_sheds": {"best_effort": 0},
        "fleet_brownout": {"stage": 0, "stages": {"r0": 0, "r1": 0}},
    }
    registry = CollectorRegistry()
    register_replica_router(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    assert val("router_requests_total", replica="r1", route="affine",
               role="decode") == 7
    assert val("router_requests_total", replica="r0", route="affine",
               role="prefill") == 1
    assert val("router_ejections_total", replica="r0", role="prefill") == 2
    assert val("router_role_members", role="prefill") == 1
    assert val("router_role_members", role="decode") == 1
    # a member leaving the ring moves the role gauge on the next scrape
    stats["ring"] = ["r1"]
    assert val("router_role_members", role="prefill") == 0


def test_engine_kv_ship_metrics_exported():
    """engine_kv_ship_pages_total{direction} / engine_kv_ship_ms /
    engine_kv_ship_hit_rate from a synthetic lifecycle provider carrying
    the kv_ship block (docs/disaggregation.md)."""
    from clearml_serving_tpu.statistics.metrics import (
        register_engine_lifecycle,
    )

    stats = {
        "model": "m1",
        "replica": "r1",
        "queue_depth": 0,
        "active_slots": 0,
        "ready": 1,
        "kv_ship": {
            "role": "decode",
            "ships": 0, "ship_pages": 0, "ship_drops": 0,
            "receives": 4, "receive_pages": 9,
            "receive_empty": 1, "receive_failures": 0,
            "hits": 4, "recomputes": 1, "hit_rate": 0.8,
            "ship_ms": {"buckets": [1, 5], "counts": [0, 0, 0],
                        "sum_ms": 0.0},
            "receive_ms": {"buckets": [1, 5], "counts": [2, 1, 1],
                           "sum_ms": 12.5},
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(
            name, {"model": "m1", "replica": "r1", **labels}
        )

    assert val("engine_kv_ship_pages_total", direction="out") == 0
    assert val("engine_kv_ship_pages_total", direction="in") == 9
    assert val("engine_kv_ship_hit_rate") == 0.8
    assert val("engine_kv_ship_ms_count", direction="in") == 4
    assert val("engine_kv_ship_ms_sum", direction="in") == 12.5
    # counters move on the next scrape
    stats["kv_ship"]["receive_pages"] = 12
    assert val("engine_kv_ship_pages_total", direction="in") == 12


def test_disagg_fleet_real_engine_end_to_end():
    """End to end against a REAL prefill/decode-split group: the decode
    replica's lifecycle provider exports the ship families after a
    disaggregated request actually shipped (docs/disaggregation.md)."""
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
    from clearml_serving_tpu.llm.replica import ReplicaGroup
    from clearml_serving_tpu.statistics.metrics import (
        register_engine_lifecycle,
        register_replica_router,
    )

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engines = [
        LLMEngineCore(
            bundle, params, replica="r{}".format(i), max_batch=2,
            max_seq_len=128, prefill_buckets=[32, 64], eos_token_id=None,
            cache_mode="paged", page_size=16, prefix_cache=64,
            prefix_block=16, num_pages=65,
        )
        for i in range(2)
    ]
    group = ReplicaGroup(engines, roles=["prefill", "decode"])
    try:
        registry = CollectorRegistry()
        for replica in group.replicas:

            def provider(engine=replica.engine):
                s = engine.lifecycle_stats()
                s["model"] = "fleet"
                return s

            register_engine_lifecycle(
                provider, registry=registry, key="fleet@" + replica.name
            )
        register_replica_router(
            lambda: dict(group.router.stats(), model="fleet"),
            registry=registry, key="fleet",
        )

        async def run():
            conv = [(5 + i * 3) % 90 + 1 for i in range(40)]
            request = GenRequest(prompt_ids=conv, max_new_tokens=2)
            async for _ in group.generate(request):
                pass
            await group.wait_drained()

        asyncio.run(run())

        def val(name, **labels):
            return registry.get_sample_value(
                name, {"model": "fleet", **labels}
            )

        assert val("engine_kv_ship_pages_total", replica="r0",
                   direction="out") >= 1
        assert val("engine_kv_ship_pages_total", replica="r1",
                   direction="in") >= 1
        assert val("engine_kv_ship_hit_rate", replica="r1") == 1.0
        assert val("router_role_members", role="decode") == 1
        assert val("router_role_members", role="prefill") == 1
        assert val("router_requests_total", replica="r1", route="affine",
                   role="decode") == 1
    finally:
        group.stop()


def test_replica_fleet_real_engine_end_to_end():
    """End to end against a REAL 2-replica group: per-replica lifecycle
    providers (replica label from the engine's own lifecycle_stats) and
    the router provider feed one registry, exactly as openai_api wires
    them."""
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
    from clearml_serving_tpu.llm.replica import ReplicaGroup
    from clearml_serving_tpu.statistics.metrics import (
        register_engine_lifecycle,
        register_replica_router,
    )

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engines = [
        LLMEngineCore(
            bundle, params, replica="r{}".format(i), max_batch=2,
            max_seq_len=64,
            prefill_buckets=[32], eos_token_id=None, cache_mode="paged",
            page_size=16, prefix_cache=32, prefix_block=16,
        )
        for i in range(2)
    ]
    group = ReplicaGroup(engines)
    try:
        registry = CollectorRegistry()
        for replica in group.replicas:

            def provider(engine=replica.engine):
                s = engine.lifecycle_stats()
                s["model"] = "fleet"
                return s

            register_engine_lifecycle(
                provider, registry=registry, key="fleet@" + replica.name
            )
        register_replica_router(
            lambda: dict(group.router.stats(), model="fleet"),
            registry=registry, key="fleet",
        )

        async def run():
            conv = [(5 + i * 3) % 90 + 1 for i in range(40)]
            for turn in range(2):
                request = GenRequest(
                    prompt_ids=conv + [7] * (turn + 1), max_new_tokens=2
                )
                async for _ in group.generate(request):
                    pass
            await group.wait_drained()
            return request._replica_name

        home = asyncio.run(run())

        def val(name, **labels):
            return registry.get_sample_value(name, {"model": "fleet", **labels})

        assert val("engine_ready", replica="r0") == 1
        assert val("engine_ready", replica="r1") == 1
        assert val("router_ring_size") == 2
        home_id = home  # "r0"/"r1"
        assert val("router_requests_total", replica=home_id,
                   route="affine", role="hybrid") == 2
    finally:
        group.stop()


def test_prune_entries_drops_stale_replica_keys():
    """Endpoint hot-reloads that change the replica count must not leave
    stale per-replica collector entries (docs/replication.md): a fleet
    scaled down (or reloaded as a single engine) prunes its model@rN
    entries — nothing pins dead engines' caches or exports frozen
    series — while OTHER endpoints' entries are untouched."""
    from clearml_serving_tpu.statistics.metrics import (
        prune_engine_lifecycle,
        register_engine_lifecycle,
    )

    registry = CollectorRegistry()
    for key in ("m@r0", "m@r1", "m@r2", "m", "m2@r0", "m2"):
        register_engine_lifecycle(
            lambda key=key: {"queue_depth": 1}, registry=registry, key=key
        )
    # reload to 2 replicas: bare "m" and "m@r2" go, r0/r1 stay, m2* stays
    prune_engine_lifecycle("m", {"m@r0", "m@r1"}, registry=registry)

    def has(key):
        label = {"model": key, "class": "all"}
        return registry.get_sample_value("engine_queue_depth", label) is not None

    assert has("m@r0") and has("m@r1")
    assert not has("m@r2") and not has("m")
    assert has("m2@r0") and has("m2")
    # reload to a single engine: every m@rN goes
    register_engine_lifecycle(
        lambda: {"queue_depth": 3}, registry=registry, key="m"
    )
    prune_engine_lifecycle("m", {"m"}, registry=registry)
    assert has("m") and not has("m@r0") and not has("m@r1")


def test_prefix_cache_collector_replica_label_split():
    """Fleet prefix-cache entries carry the {model, replica} label split
    (docs/replication.md) — never a mangled model label — while legacy
    entries on the same collector keep the historical {model} shape."""
    from clearml_serving_tpu.llm.kv_cache import PagePool
    from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache
    from clearml_serving_tpu.statistics.metrics import register_prefix_cache

    registry = CollectorRegistry()
    pool = PagePool(num_pages=16, page_size=2, max_slots=2)
    cache_r0 = RadixPrefixCache(block=4, pool=pool, page_bytes=32)
    cache_r1 = RadixPrefixCache(block=4)
    legacy = RadixPrefixCache(block=4)
    register_prefix_cache(cache_r0, pool, registry=registry,
                          key="fleet@r0", model="fleet", replica="r0")
    register_prefix_cache(cache_r1, registry=registry,
                          key="fleet@r1", model="fleet", replica="r1")
    register_prefix_cache(legacy, registry=registry, key="plain")

    cache_r0.lookup_pages([1, 2, 3, 4, 5, 6], 0)  # miss
    legacy.lookup([9, 9, 9, 9, 9], 0)             # miss

    def val(name, **labels):
        return registry.get_sample_value(name, labels)

    # fleet rows: real model label + replica label (joinable with the
    # lifecycle/router families on (model, replica))
    assert val("llm_prefix_cache_misses_total",
               model="fleet", replica="r0") == 1
    assert val("llm_prefix_cache_misses_total",
               model="fleet", replica="r1") == 0
    assert val("kv_pool_free_pages", model="fleet", replica="r0") is not None
    # no mangled model label anywhere
    assert val("llm_prefix_cache_misses_total", model="fleet@r0") is None
    # the legacy entry's series identity is untouched
    assert val("llm_prefix_cache_misses_total", model="plain") == 1


def test_engine_kv_wire_metrics_exported():
    """engine_kv_ship_wire_bytes_total{direction} + engine_kv_ship_rtt_ms
    from a synthetic lifecycle provider whose kv_ship block carries the
    socket transport's wire sub-block (llm/kv_wire.py); providers on the
    in-heap backend (no wire block) must not emit the families at all."""
    from clearml_serving_tpu.statistics.metrics import (
        register_engine_lifecycle,
    )

    stats = {
        "model": "m1",
        "replica": "r1",
        "queue_depth": 0,
        "active_slots": 0,
        "ready": 1,
        "kv_ship": {
            "role": "decode",
            "ships": 1, "ship_pages": 2, "ship_drops": 0,
            "receives": 1, "receive_pages": 2,
            "receive_empty": 0, "receive_failures": 0,
            "hits": 1, "recomputes": 0, "hit_rate": 1.0,
            "ship_ms": {"buckets": [1, 5], "counts": [1, 0, 0],
                        "sum_ms": 0.5},
            "receive_ms": {"buckets": [1, 5], "counts": [1, 0, 0],
                           "sum_ms": 0.5},
            "transport": {
                "backend": "socket_slab",
                "wire": {
                    "bytes_sent": 4096, "bytes_received": 1024,
                    "frames_sent": 2, "frames_received": 1,
                    "send_failures": 0, "recv_failures": 0,
                    "rtt_ms": {"buckets": [1.0, 5.0],
                               "counts": [1, 1, 0], "sum_ms": 3.5,
                               "count": 2},
                },
            },
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(
            name, {"model": "m1", "replica": "r1", **labels}
        )

    assert val("engine_kv_ship_wire_bytes_total", direction="out") == 4096
    assert val("engine_kv_ship_wire_bytes_total", direction="in") == 1024
    assert val("engine_kv_ship_rtt_ms_count") == 2
    assert val("engine_kv_ship_rtt_ms_sum") == 3.5
    assert val("engine_kv_ship_rtt_ms_bucket", le="1.0") == 1
    assert val("engine_kv_ship_rtt_ms_bucket", le="5.0") == 2
    assert val("engine_kv_ship_rtt_ms_bucket", le="+Inf") == 2
    # counters move on the next scrape
    stats["kv_ship"]["transport"]["wire"]["bytes_sent"] = 8192
    assert val("engine_kv_ship_wire_bytes_total", direction="out") == 8192
    # a shared-slab provider (no wire block) does not emit the families
    registry2 = CollectorRegistry()
    shared = dict(stats)
    shared["kv_ship"] = dict(stats["kv_ship"], transport={"backend": "shared_slab"})
    register_engine_lifecycle(lambda: shared, registry=registry2, key="m1")
    assert registry2.get_sample_value(
        "engine_kv_ship_wire_bytes_total",
        {"model": "m1", "replica": "r1", "direction": "out"},
    ) is None


def test_router_replica_backend_info_gauge():
    """router_replica_backend{model,backend} = 1: the info-style gauge a
    dashboard joins on to tell process fleets from in-process ones
    (docs/replication.md)."""
    from clearml_serving_tpu.statistics.metrics import register_replica_router

    stats = {
        "replicas": 2,
        "ring_size": 2,
        "replica_backend": "process",
        "requests": {},
    }
    registry = CollectorRegistry()
    register_replica_router(lambda: stats, registry=registry, key="m1")
    assert registry.get_sample_value(
        "router_replica_backend", {"model": "m1", "backend": "process"}
    ) == 1
    assert registry.get_sample_value(
        "router_replica_backend", {"model": "m1", "backend": "inprocess"}
    ) is None
    # live: a (hypothetical) backend change moves the label on next scrape
    stats["replica_backend"] = "inprocess"
    assert registry.get_sample_value(
        "router_replica_backend", {"model": "m1", "backend": "inprocess"}
    ) == 1


@pytest.mark.slow
def test_socket_fleet_wire_metrics_end_to_end():
    """End to end against a REAL prefill/decode group on the SOCKET
    transport backend: after a disaggregated request ships over the wire,
    the prefill replica exports wire bytes out + an RTT sample, the
    decode replica exports wire bytes in, and the router carries the
    backend info gauge."""
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
    from clearml_serving_tpu.llm.replica import ReplicaGroup
    from clearml_serving_tpu.statistics.metrics import (
        register_engine_lifecycle,
        register_replica_router,
    )

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engines = [
        LLMEngineCore(
            bundle, params, replica="r{}".format(i), max_batch=2,
            max_seq_len=128, prefill_buckets=[32, 64], eos_token_id=None,
            cache_mode="paged", page_size=16, prefix_cache=64,
            prefix_block=16, num_pages=65,
        )
        for i in range(2)
    ]
    group = ReplicaGroup(
        engines, roles=["prefill", "decode"], kv_transport_backend="socket"
    )
    try:
        registry = CollectorRegistry()
        for replica in group.replicas:

            def provider(engine=replica.engine):
                s = engine.lifecycle_stats()
                s["model"] = "fleet"
                return s

            register_engine_lifecycle(
                provider, registry=registry, key="fleet@" + replica.name
            )
        register_replica_router(
            lambda: dict(group.router.stats(), model="fleet"),
            registry=registry, key="fleet",
        )

        async def run():
            conv = [(5 + i * 3) % 90 + 1 for i in range(40)]
            request = GenRequest(prompt_ids=conv, max_new_tokens=2)
            async for _ in group.generate(request):
                pass
            await group.wait_drained()

        asyncio.run(run())

        def val(name, **labels):
            return registry.get_sample_value(
                name, {"model": "fleet", **labels}
            )

        assert val("engine_kv_ship_wire_bytes_total", replica="r0",
                   direction="out") > 0
        assert val("engine_kv_ship_rtt_ms_count", replica="r0") >= 1
        assert val("engine_kv_ship_wire_bytes_total", replica="r1",
                   direction="in") > 0
        assert val("engine_kv_ship_hit_rate", replica="r1") == 1.0
        assert val("router_replica_backend", backend="inprocess") == 1
    finally:
        group.stop()


def test_engine_spec_tree_and_draft_ahead_metrics_exported():
    """Tree-draft + draft-ahead observability (docs/spec_decode_trees.md):
    engine_spec_tree_accept_depth histogram,
    engine_spec_proposer_hits_total{proposer} counter and the
    engine_kv_ship_overlap_ratio gauge — from a synthetic lifecycle
    provider AND end to end against a real tree-spec engine."""
    from clearml_serving_tpu.statistics.metrics import register_engine_lifecycle

    stats = {
        "queue_depth": 0,
        "ragged": {
            "step_token_budget": 16,
            "effective_budget": 16,
            "prefill_jobs": 0,
            "steps": 3,
            "step_rows": {"spec_verify": 3},
            "spec_tree_depth": {
                "buckets": [0, 1, 2, 3, 4],
                "counts": [1, 0, 2, 1, 0, 0],
                "sum_ms": 7.0,
                "count": 4,
            },
            "spec_tree_fallbacks": 0,
            "spec_proposer": {
                "name": "ngram-forest", "proposed": 9, "hit": 6,
                "branched": 4,
            },
        },
        "kv_ship": {
            "ships": 2, "ship_pages": 8, "ship_drops": 0,
            "draft_ships": 3, "draft_pages": 6, "draft_aborts": 0,
            "overlap_ratio": 0.75,
        },
    }
    registry = CollectorRegistry()
    register_engine_lifecycle(lambda: stats, registry=registry, key="m1")

    def val(name, **labels):
        return registry.get_sample_value(name, {"model": "m1", **labels})

    # accepted-depth histogram: cumulative buckets + count/sum
    assert val("engine_spec_tree_accept_depth_count") == 4
    assert val("engine_spec_tree_accept_depth_sum") == 7.0
    assert val("engine_spec_tree_accept_depth_bucket", le="2") == 3
    assert val("engine_spec_tree_accept_depth_bucket", le="+Inf") == 4
    # proposer hits carry the backend label
    assert val(
        "engine_spec_proposer_hits_total", proposer="ngram-forest"
    ) == 6
    # draft-ahead overlap: shipped-before-commit / all shipped pages
    assert val("engine_kv_ship_overlap_ratio") == 0.75

    # chain / non-tree providers (spec_tree_depth None, no proposer dict)
    # skip the tree families without breaking the ragged block
    registry2 = CollectorRegistry()
    register_engine_lifecycle(
        lambda: {
            "queue_depth": 0,
            "ragged": {"spec_tree_depth": None, "spec_proposer": None,
                       "step_rows": {"decode": 2}},
        },
        registry=registry2, key="m2",
    )
    assert registry2.get_sample_value(
        "engine_spec_tree_accept_depth_count", {"model": "m2"}
    ) is None
    assert registry2.get_sample_value(
        "engine_step_rows_total", {"model": "m2", "phase": "decode"}
    ) == 2

    # end to end: a real tree-spec engine feeds the same families
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, scheduler="ragged", step_token_budget=12,
        cache_mode="paged", speculation="ngram", spec_k=4, spec_ngram=2,
        spec_tree=True, spec_branch=2,
    )
    try:
        registry3 = CollectorRegistry()
        register_engine_lifecycle(
            engine.lifecycle_stats, registry=registry3, key="llm"
        )

        async def run():
            req = GenRequest(
                prompt_ids=[5, 9, 2, 17, 5, 9, 2], max_new_tokens=8
            )
            out = [t async for t in engine.generate(req)]
            await engine.wait_drained()
            return out

        out = asyncio.run(run())
        assert len(out) == 8

        def rval(name, **labels):
            return registry3.get_sample_value(
                name, {"model": "llm", **labels}
            )

        assert rval("engine_step_rows_total", phase="spec_verify") >= 1
        assert rval("engine_spec_tree_accept_depth_count") >= 1
        assert rval(
            "engine_spec_proposer_hits_total", proposer="ngram-forest"
        ) is not None
    finally:
        engine.stop()
