"""OpenAI tool / function calling on v1/chat/completions.

Reference surface: vLLM tool parsing enabled via chat_settings
(/root/reference/clearml_serving/serving/preprocess_service.py:792-808,
/root/reference/examples/vllm/preprocess.py:25-33). Here arguments for
forced/required calls are enforced by the on-device guided-decoding DFA."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from clearml_serving_tpu.llm.tools import (
    messages_with_tool_results,
    parse_tool_calls,
    render_chat_with_tools,
    resolve_tool_choice,
    tool_call_schema,
    tools_preamble,
    validate_tools,
)
from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.main import build_app
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor

WEATHER = {
    "type": "function",
    "function": {
        "name": "get_weather",
        "description": "Look up the weather",
        "parameters": {
            "type": "object",
            "properties": {"location": {"enum": ["paris", "tokyo"]}},
            "required": ["location"],
        },
    },
}
CLOCK = {
    "type": "function",
    "function": {"name": "get_time", "parameters": {"type": "object",
                                                    "properties": {}}},
}


# ------------------------------------------------------------------ unit

def test_validate_tools_normalizes_and_rejects():
    out = validate_tools([WEATHER, CLOCK])
    assert [t["name"] for t in out] == ["get_weather", "get_time"]
    assert out[0]["parameters"]["required"] == ["location"]
    for bad in [
        [],
        [{"type": "retrieval"}],
        [{"type": "function", "function": {}}],
        [{"type": "function", "function": {"name": "x", "parameters": 3}}],
        [WEATHER, WEATHER],  # duplicate names
    ]:
        with pytest.raises(ValueError):
            validate_tools(bad)


def test_resolve_tool_choice_modes():
    assert resolve_tool_choice({}) == ("none", None)
    assert resolve_tool_choice({"tools": [WEATHER]}) == ("auto", None)
    assert resolve_tool_choice({"tools": [WEATHER], "tool_choice": "none"}) == ("none", None)
    assert resolve_tool_choice({"tools": [WEATHER], "tool_choice": "required"}) == ("required", None)
    assert resolve_tool_choice(
        {"tools": [WEATHER],
         "tool_choice": {"type": "function", "function": {"name": "get_weather"}}}
    ) == ("forced", "get_weather")
    with pytest.raises(ValueError):
        resolve_tool_choice({"tool_choice": "required"})  # tools absent
    with pytest.raises(ValueError):
        resolve_tool_choice({"tools": [WEATHER], "tool_choice": {"type": "function"}})
    with pytest.raises(ValueError):  # unknown object shape must 422, not force
        resolve_tool_choice(
            {"tools": [WEATHER],
             "tool_choice": {"type": "retrieval",
                             "function": {"name": "get_weather"}}}
        )


def test_tool_call_schema_shapes():
    tools = validate_tools([WEATHER, CLOCK])
    one = tool_call_schema(tools, "get_weather")
    assert one["properties"]["name"] == {"const": "get_weather"}
    assert one["required"] == ["name", "arguments"]
    both = tool_call_schema(tools, None)
    assert {v["properties"]["name"]["const"] for v in both["anyOf"]} == {
        "get_weather", "get_time"
    }
    with pytest.raises(ValueError):
        tool_call_schema(tools, "nope")


def test_tool_call_schema_closes_argument_objects():
    """OpenAI strict-tool-call semantics: argument schemas pin
    additionalProperties: false AND type: object (a bare `parameters: {}`
    has neither key), and the guided lowering turns the closed propertyless
    object into exactly `{}` — a free-form object would let a constrained
    decode wander until max_tokens instead of finishing the call."""
    import re as _re

    from clearml_serving_tpu.llm.guided import json_schema_to_regex

    bare = {
        "type": "function",
        "function": {"name": "noop", "parameters": {}},
    }
    schema = tool_call_schema(validate_tools([bare]), None)
    args = schema["properties"]["arguments"]
    assert args["additionalProperties"] is False
    assert args["type"] == "object"
    pattern = _re.compile(json_schema_to_regex(args) + r"\Z")
    assert pattern.match("{}")
    assert pattern.match("{ }")
    assert not pattern.match('{"surprise": 1}')
    assert not pattern.match("42")


def test_parse_tool_calls_formats():
    names = ["get_weather", "get_time"]
    # bare llama-3-style JSON, `arguments` or `parameters`
    got = parse_tool_calls('{"name": "get_weather", "arguments": {"location": "paris"}}', names)
    assert got == [{"name": "get_weather", "arguments": '{"location": "paris"}'}]
    got = parse_tool_calls('{"name": "get_time", "parameters": {}}', names)
    assert got == [{"name": "get_time", "arguments": "{}"}]
    # arguments already a JSON string
    got = parse_tool_calls('{"name": "get_time", "arguments": "{}"}', names)
    assert got == [{"name": "get_time", "arguments": "{}"}]
    # hermes/qwen <tool_call> blocks, multiple = parallel calls
    text = ('<tool_call>{"name": "get_weather", "arguments": {"location": "tokyo"}}</tool_call>\n'
            '<tool_call>{"name": "get_time", "arguments": {}}</tool_call>')
    got = parse_tool_calls(text, names)
    assert [c["name"] for c in got] == ["get_weather", "get_time"]
    # JSON array of calls
    got = parse_tool_calls('[{"name": "get_time", "arguments": {}}]', names)
    assert [c["name"] for c in got] == ["get_time"]
    # NOT tool calls: prose, unknown name, JSON without a name
    assert parse_tool_calls("the weather is nice", names) is None
    assert parse_tool_calls('{"name": "other_fn", "arguments": {}}', names) is None
    assert parse_tool_calls('{"answer": 42}', names) is None
    assert parse_tool_calls('{"name": "get_time"', names) is None  # truncated
    # arguments must be a JSON OBJECT — scalars/arrays (raw or encoded)
    # would hand OpenAI clients a non-object payload
    assert parse_tool_calls('{"name": "get_time", "arguments": "5"}', names) is None
    assert parse_tool_calls('{"name": "get_time", "arguments": "[1]"}', names) is None
    assert parse_tool_calls('{"name": "get_time", "arguments": 5}', names) is None
    assert parse_tool_calls('{"name": "get_time", "arguments": [1]}', names) is None
    assert parse_tool_calls('{"name": "get_time", "arguments": "not json"}', names) is None


def test_messages_with_tool_results_rewrite():
    msgs = [
        {"role": "user", "content": "weather?"},
        {"role": "assistant", "tool_calls": [
            {"id": "call_1", "type": "function",
             "function": {"name": "get_weather", "arguments": '{"location": "paris"}'}}]},
        {"role": "tool", "tool_call_id": "call_1", "content": "sunny"},
    ]
    out = messages_with_tool_results(msgs)
    assert out[0] == msgs[0]
    assert out[1]["role"] == "assistant" and "get_weather" in out[1]["content"]
    assert out[2]["role"] == "user" and "sunny" in out[2]["content"]


def test_render_falls_back_to_preamble():
    from clearml_serving_tpu.llm.tokenizer import ByteTokenizer

    tok = ByteTokenizer(512)
    tools = validate_tools([WEATHER])
    text = render_chat_with_tools(tok, [{"role": "user", "content": "hi"}], tools)
    assert "get_weather" in text and "respond ONLY with a JSON object" in text
    pre = tools_preamble(tools)
    assert "get_weather" in pre and "location" in pre


# ------------------------------------------------------------------ HTTP

@pytest.fixture(scope="module")
def tool_served(tmp_path_factory):
    import os

    root = tmp_path_factory.mktemp("state")
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    mrp = ModelRequestProcessor(state_root=str(root), force_create=True, name="llm")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="tiny_llm",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 2,
                    "max_seq_len": 1024,
                    "prefill_buckets": [128],
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def _run(mrp, fn):
    async def runner():
        client = TestClient(TestServer(build_app(mrp)))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


def _chat_body(**extra):
    body = {
        "model": "tiny_llm",
        "messages": [{"role": "user", "content": "weather in paris?"}],
        "max_tokens": 96,
        "temperature": 0.9,
        "seed": 7,
        "tools": [WEATHER, CLOCK],
    }
    body.update(extra)
    return body


def test_forced_tool_call_http(tool_served):
    """tool_choice forcing one function: the guided DFA makes the call and
    its arguments schema-valid by construction (OpenAI SDK wire shape)."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(tool_choice={"type": "function",
                                         "function": {"name": "get_weather"}}),
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(tool_served, fn)
    choice = out["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    msg = choice["message"]
    assert msg["content"] is None
    (call,) = msg["tool_calls"]
    assert call["id"].startswith("call_") and call["type"] == "function"
    assert call["function"]["name"] == "get_weather"
    args = json.loads(call["function"]["arguments"])
    assert args["location"] in ("paris", "tokyo")


def test_required_tool_call_http(tool_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(tool_choice="required", seed=11),
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(tool_served, fn)
    choice = out["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    (call,) = choice["message"]["tool_calls"]
    assert call["function"]["name"] in ("get_weather", "get_time")
    json.loads(call["function"]["arguments"])


def test_forced_tool_call_streaming(tool_served):
    """SSE shape: role chunk, tool_calls deltas accumulating by index,
    finish_reason tool_calls."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(stream=True,
                            tool_choice={"type": "function",
                                         "function": {"name": "get_weather"}}),
        )
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        return await r.text()

    text = _run(tool_served, fn)
    lines = [l for l in text.split("\n\n") if l.startswith("data: ")]
    assert lines[-1] == "data: [DONE]"
    chunks = [json.loads(l[len("data: "):]) for l in lines[:-1]]
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    # accumulate tool_call deltas the way the OpenAI SDK does
    acc = {}
    finish = None
    for c in chunks:
        ch = c["choices"][0]
        finish = ch.get("finish_reason") or finish
        for tc in ch["delta"].get("tool_calls") or []:
            slot = acc.setdefault(tc["index"], {"id": None, "name": "", "arguments": ""})
            if tc.get("id"):
                slot["id"] = tc["id"]
            fn_part = tc.get("function") or {}
            if fn_part.get("name"):
                slot["name"] = fn_part["name"]
            slot["arguments"] += fn_part.get("arguments", "")
    assert finish == "tool_calls"
    assert acc[0]["name"] == "get_weather" and acc[0]["id"].startswith("call_")
    args = json.loads(acc[0]["arguments"])
    assert args["location"] in ("paris", "tokyo")


def test_auto_mode_plain_answer_http(tool_served):
    """auto + a model that answers in prose: normal content response, no
    tool_calls fabricated."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(temperature=0.0, max_tokens=8),
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(tool_served, fn)
    choice = out["choices"][0]
    assert choice["finish_reason"] != "tool_calls"
    assert "tool_calls" not in choice["message"]
    assert isinstance(choice["message"]["content"], str)


def test_auto_tools_with_guided_json_streams_incrementally(tool_served):
    """tools auto + response_format json_object: the output is guaranteed
    to start with '{' WITHOUT being a tool call, so the call-prefix sniff
    must be disabled and content must stream as it decodes — not buffer to
    a single end-of-stream chunk (r4 advisor finding)."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(stream=True, max_tokens=48,
                            response_format={"type": "json_object"}),
        )
        assert r.status == 200, await r.text()
        return await r.text()

    text = _run(tool_served, fn)
    lines = [l for l in text.split("\n\n") if l.startswith("data: ")]
    chunks = [json.loads(l[len("data: "):]) for l in lines[:-1]]
    content_chunks = [
        c for c in chunks
        if c["choices"] and c["choices"][0]["delta"].get("content")
    ]
    # incremental streaming: content arrives across multiple deltas
    assert len(content_chunks) >= 2, [c["choices"][0]["delta"] for c in chunks]
    assert not any(
        c["choices"][0]["delta"].get("tool_calls")
        for c in chunks if c["choices"]
    )
    body = "".join(
        c["choices"][0]["delta"]["content"] for c in content_chunks
    )
    assert body.lstrip().startswith("{")


def test_auto_tools_with_guided_json_nonstreaming_stays_content(tool_served):
    """Non-streaming twin of the streaming test above: with a body-supplied
    guided response_format, the JSON answer is the deliverable — it must not
    be re-parsed into tool_calls (stream and non-stream responses stay
    structurally identical)."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(max_tokens=48,
                            response_format={"type": "json_object"}),
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(tool_served, fn)
    choice = out["choices"][0]
    assert choice["finish_reason"] != "tool_calls"
    assert "tool_calls" not in choice["message"]
    assert choice["message"]["content"].lstrip().startswith("{")


def test_tool_errors_http(tool_served):
    async def fn(client):
        # tool_choice without tools
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={"model": "tiny_llm",
                  "messages": [{"role": "user", "content": "x"}],
                  "tool_choice": "required"},
        )
        assert r.status == 422, await r.text()
        # malformed tool entry
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(tools=[{"type": "function", "function": {}}]),
        )
        assert r.status == 422, await r.text()
        # forcing an unknown tool
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(tool_choice={"type": "function",
                                         "function": {"name": "nope"}}),
        )
        assert r.status == 422, await r.text()

    _run(tool_served, fn)


def test_parse_tool_calls_with_surrounding_prose():
    """Hermes models narrate before calling: prose + <tool_call> blocks
    must yield calls AND preserve the prose (r4 code review)."""
    from clearml_serving_tpu.llm.tools import strip_tool_blocks

    text = ('Let me check that for you.\n'
            '<tool_call>{"name": "get_weather", "arguments": {"location": "paris"}}</tool_call>')
    calls = parse_tool_calls(text, ["get_weather"])
    assert calls and calls[0]["name"] == "get_weather"
    assert strip_tool_blocks(text) == "Let me check that for you."


def test_split_tag_holdback():
    from clearml_serving_tpu.llm.tools import split_tag_holdback

    assert split_tag_holdback("hello ") == ("hello ", "")
    assert split_tag_holdback("hello <tool") == ("hello ", "<tool")
    assert split_tag_holdback("<") == ("", "<")
    # a '<' that can't start the tag is emitted
    assert split_tag_holdback("a < b") == ("a < b", "")


def test_tool_grammar_forces_name_before_arguments():
    """r4 code review: the serialized grammar schema must keep declaration
    order (name first) — sort_keys would make the model commit arguments
    before the tool name is pinned."""
    tools = validate_tools([WEATHER, CLOCK])
    payload = json.dumps(tool_call_schema(tools, None))
    for variant in json.loads(payload)["anyOf"]:
        keys = list(variant["properties"].keys())
        assert keys == ["name", "arguments"]
    assert payload.index('"name"') < payload.index('"arguments"')


def test_prose_then_tool_call_streaming(tool_served):
    """Streaming auto mode must detect a <tool_call> tag arriving AFTER
    prose (r4 code review): the tag text never streams as content and the
    stream finishes with tool_calls.

    The tiny random model can't emit the tag itself, so this drives the
    SSE state machine through the route with a stop-gated two-phase hack:
    instead we test the watcher pieces directly."""
    from clearml_serving_tpu.llm.tools import split_tag_holdback

    # simulate the sse watcher: prose streams, tag switches to buffering
    pending = ""
    emitted = []
    deltas = ["Sure, ", "let me <to", "ol_call>{\"name\": \"get_time\"", ", \"arguments\": {}}</tool_call>"]
    buffered = None
    for d in deltas:
        if buffered is not None:
            buffered += d
            continue
        pending += d
        idx = pending.find("<tool_call>")
        if idx >= 0:
            emitted.append(pending[:idx])
            buffered = pending[idx:]
            pending = ""
        else:
            emit, pending = split_tag_holdback(pending)
            if emit:
                emitted.append(emit)
    assert "".join(emitted) == "Sure, let me "
    calls = parse_tool_calls(buffered, ["get_time"])
    assert calls == [{"name": "get_time", "arguments": "{}"}]


def test_no_tools_history_passes_messages_untouched():
    """r4 code review: without a `tools` field the messages must reach the
    template unrewritten so tool-native templates render real tool turns."""

    class _Spy:
        def __init__(self):
            self.seen = None

        def apply_chat_template(self, messages, tools=None):
            self.seen = messages
            return "x"

    spy = _Spy()
    msgs = [
        {"role": "user", "content": "hi"},
        {"role": "tool", "tool_call_id": "c1", "content": "sunny"},
    ]
    render_chat_with_tools(spy, msgs, [])
    assert spy.seen is msgs  # untouched, not rewritten


def test_failed_tools_render_falls_back_to_preamble():
    """r4 code review: a tokenizer whose tools= render fails must yield
    the PREAMBLE path, never a degraded non-template render."""

    class _FakeHF:
        def apply_chat_template(self, messages, tokenize=False,
                                add_generation_prompt=True, tools=None):
            if tools is not None:
                raise TypeError("no tools kwarg")  # old transformers
            return "<T>" + " ".join(m.get("content") or "" for m in messages)

    from clearml_serving_tpu.llm.tokenizer import HFTokenizer

    tok = HFTokenizer.__new__(HFTokenizer)
    tok._tok = _FakeHF()
    tools = validate_tools([WEATHER])
    text = render_chat_with_tools(tok, [{"role": "user", "content": "hi"}], tools)
    assert "get_weather" in text  # preamble injected
    assert tok._tools_template_native is False


def test_parallel_tool_calls_false_caps_auto_mode(tool_served, monkeypatch):
    """OpenAI `parallel_tool_calls: false` restricts AUTO-mode parses to a
    single call. A tiny random model won't reliably emit two <tool_call>
    blocks, so the parser is stubbed to return two calls — pinning the
    route-level cap itself (delete the cap and this fails)."""
    from clearml_serving_tpu.llm import tools as tools_mod

    two = [
        {"name": "get_weather", "arguments": '{"location": "tokyo"}'},
        {"name": "get_time", "arguments": "{}"},
    ]
    monkeypatch.setattr(
        tools_mod, "parse_tool_calls", lambda text, names=None: list(two)
    )

    async def fn(client):
        # bias the EOS token so finish_reason is "stop" (a length-cut
        # response is never parsed for calls, per OpenAI semantics)
        eos_bias = {"logit_bias": {"257": 200.0}, "max_tokens": 6}
        on = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(parallel_tool_calls=False, **eos_bias),
        )
        off = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(**eos_bias),
        )
        assert on.status == 200 and off.status == 200
        return await on.json(), await off.json()

    capped, free = _run(tool_served, fn)
    assert len(capped["choices"][0]["message"]["tool_calls"]) == 1
    assert len(free["choices"][0]["message"]["tool_calls"]) == 2


def test_parallel_tool_calls_false_http(tool_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(tool_choice="required", seed=11,
                            parallel_tool_calls=False),
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(tool_served, fn)
    choice = out["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    assert len(choice["message"]["tool_calls"]) == 1
