"""Multi-container topology integration: the real router and engine-server
entrypoints run as SEPARATE processes (the compose-tpu-engine.yaml wiring),
sharing only the state volume — HTTP -> router -> gRPC -> engine -> XLA.

The reference's acceptance equivalent is bringing up docker-compose-triton
and curling an endpoint; here the same service commands run as processes
(docker itself isn't available in CI)."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

REPO = str(Path(__file__).resolve().parent.parent)

LAUNCHER = """
import sys

sys.path.insert(0, {repo!r})
import jax

jax.config.update("jax_platforms", "cpu")
from clearml_serving_tpu.{module} import main

main()
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_http(url: str, timeout: float = 60.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            return urllib.request.urlopen(url, timeout=5)
        except Exception as ex:
            last = ex
            time.sleep(0.5)
    raise AssertionError("service at {} never came up: {}".format(url, last))


def test_router_and_engine_as_separate_processes(tmp_path, state_root):
    from clearml_serving_tpu import models
    from clearml_serving_tpu.engines.jax_engine import save_bundle
    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    # operator step: create service + endpoint in the shared state root
    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="topo")
    bundle = models.build_model("mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3})
    params = bundle.init(jax.random.PRNGKey(0))
    bdir = tmp_path / "bundle"
    save_bundle(bdir, "mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3}, params)
    rec = mrp.registry.register("mlp", path=bdir, framework="jax")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="jax_grpc",
            serving_url="topo_mlp",
            model_id=rec.id,
            input_name="features",
            input_type="float32",
            input_size=[4],
            output_type="float32",
            output_name="logits",
        )
    )
    http_port = _free_port()
    grpc_port = _free_port()
    mrp.configure(external_engine_grpc_address="127.0.0.1:{}".format(grpc_port))
    mrp.serialize()

    # the compose services, as processes (JAX_PLATFORMS must NOT be in the
    # env — this image's sitecustomize hangs on it; the launcher forces the
    # CPU backend in-process instead)
    env = {
        k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env.update(
        TPUSERVE_STATE_ROOT=str(state_root),
        TPUSERVE_SERVICE_ID=mrp.get_id(),
        TPUSERVE_PORT=str(http_port),
        TPUSERVE_ENGINE_PORT=str(grpc_port),
        TPUSERVE_ENGINE_METRICS_PORT="0",
        TPUSERVE_POLL_FREQ="0.02",
    )
    scripts = {}
    for role, module in (
        ("engine", "engine_server.server"),
        ("inference", "serving.main"),
    ):
        f = tmp_path / "run_{}.py".format(role)
        f.write_text(LAUNCHER.format(repo=REPO, module=module))
        scripts[role] = f

    procs = []
    logs = {}
    try:
        for role in ("engine", "inference"):
            # log to files, not PIPE: nobody drains the pipe during the test,
            # and a full 64KB buffer would block the server mid-write
            logs[role] = open(tmp_path / "{}.log".format(role), "w+")
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(scripts[role])],
                    stdout=logs[role],
                    stderr=subprocess.STDOUT,
                    text=True,
                    env=env,
                )
            )
        _wait_http("http://127.0.0.1:{}/health".format(http_port), timeout=90)
        body = json.dumps({"features": [[1, 2, 3, 4], [4, 3, 2, 1]]}).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:{}/serve/topo_mlp".format(http_port),
            body,
            {"Content-Type": "application/json"},
        )
        deadline = time.time() + 90
        out = None
        while time.time() < deadline:
            try:
                out = json.loads(urllib.request.urlopen(req, timeout=10).read())
                break
            except urllib.error.HTTPError as ex:
                # engine may still be loading the model; 422/500 until synced
                if ex.code not in (422, 500):
                    raise
                time.sleep(1.0)
        if out is None:
            details = {}
            for role, fh in logs.items():
                fh.flush()
                fh.seek(0)
                details[role] = fh.read()[-2000:]
            pytest.fail("engine never served through the router:\n{}".format(details))
        expected = bundle.apply(params, np.array([[1, 2, 3, 4], [4, 3, 2, 1]], np.float32))
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-4)
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            try:
                p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        for fh in logs.values():
            fh.close()


def test_compose_topologies_are_wellformed():
    """Every compose file parses and references only roles the entrypoint
    knows (inference/engine/statistics)."""
    yaml = pytest.importorskip("yaml")

    class ComposeLoader(yaml.SafeLoader):
        pass

    # compose-spec merge tags (!reset clears inherited sequences/maps)
    ComposeLoader.add_constructor("!reset", lambda loader, node: None)
    ComposeLoader.add_constructor(
        "!override", lambda loader, node: loader.construct_object(node)
    )

    docker_dir = Path(REPO) / "docker"
    files = sorted(
        list(docker_dir.glob("compose*.yaml")) + list(docker_dir.glob("docker-compose*.yml"))
    )
    assert len(files) >= 6, files  # topology breadth parity with the reference
    for f in files:
        data = yaml.load(f.read_text(), Loader=ComposeLoader)
        assert "services" in data or "include" in data, f
        for name, svc in (data.get("services") or {}).items():
            cmd = svc.get("command")
            if cmd and "clearml-serving-tpu" in str(svc.get("image", "")):
                assert cmd[0] in ("inference", "engine", "statistics"), (f, name, cmd)


def test_monitoring_stack_provisioned():
    """Alertmanager + alert rules + Grafana dashboard ship with the base
    topology (reference docker-compose.yml:52-57 runs alertmanager in every
    deployment; its README walks Grafana dashboards — this repo provisions
    one by default). The variant topologies `include:` the base file, so
    checking it covers all of them."""
    import json

    yaml = pytest.importorskip("yaml")
    docker_dir = Path(REPO) / "docker"

    base = yaml.safe_load((docker_dir / "docker-compose.yml").read_text())
    services = base["services"]
    assert "alertmanager" in services
    am_vols = " ".join(services["alertmanager"].get("volumes", []))
    assert "alertmanager.yml" in am_vols
    prom_vols = " ".join(services["prometheus"].get("volumes", []))
    assert "alert_rules.yml" in prom_vols
    graf_vols = " ".join(services["grafana"].get("volumes", []))
    assert "grafana-dashboards.yml" in graf_vols and "dashboards" in graf_vols

    # prometheus wiring: rules loaded, alertmanager targeted
    prom = yaml.safe_load((docker_dir / "prometheus.yml").read_text())
    assert any("alert_rules" in r for r in prom["rule_files"])
    am_targets = prom["alerting"]["alertmanagers"][0]["static_configs"][0]["targets"]
    assert any("alertmanager" in t for t in am_targets)

    # alertmanager config parses and has a default route
    am = yaml.safe_load((docker_dir / "alertmanager.yml").read_text())
    receivers = {r["name"] for r in am["receivers"]}
    assert am["route"]["receiver"] in receivers

    # alert rules parse; every rule has expr/severity; the battery covers
    # latency, error-rate, and HBM headroom (VERDICT r3 #6)
    rules = yaml.safe_load((docker_dir / "alert_rules.yml").read_text())
    alerts = {
        r["alert"]: r for g in rules["groups"] for r in g["rules"]
    }
    for want in ("RouterHighP99Latency", "EngineHighErrorRate",
                 "TPUHBMHeadroomLow", "ServingTargetDown"):
        assert want in alerts, want
        assert alerts[want]["expr"].strip()
        assert alerts[want]["labels"]["severity"] in ("warning", "critical")
    # rule expressions reference series this repo actually exports
    joined = " ".join(r["expr"] for r in alerts.values())
    assert "engine_infer_requests_total" in joined
    assert "tpu_hbm_bytes_in_use" in joined
    assert "__latency_bucket" in joined

    # grafana: provider points at the dashboards dir; dashboard JSON valid
    provider = yaml.safe_load((docker_dir / "grafana-dashboards.yml").read_text())
    path = provider["providers"][0]["options"]["path"]
    assert path.endswith("dashboards")
    dash = json.loads((docker_dir / "grafana" / "tpuserve-serving.json").read_text())
    assert dash["uid"] == "tpuserve-serving"
    exprs = " ".join(
        t["expr"] for p in dash["panels"] for t in p.get("targets", [])
    )
    for series in ("engine_infer_latency_seconds_bucket",
                   "engine_queue_delay_seconds_bucket",
                   "tpu_hbm_bytes_in_use", "__latency_bucket",
                   "__count_total"):
        assert series in exprs, series
    # every panel targets the templated datasource and has a grid position
    for p in dash["panels"]:
        assert p["datasource"]["uid"] == "${DS}"
        assert set(p["gridPos"]) == {"h", "w", "x", "y"}
