"""Whisper model/converter/mel fidelity vs transformers (audio routes)."""

import jax
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from clearml_serving_tpu import models
from clearml_serving_tpu.engines.importers.convert_hf_whisper import (
    config_from_hf,
    convert_state_dict,
)
from clearml_serving_tpu.ops.audio import (
    decode_wav,
    log_mel_spectrogram,
    mel_filter_bank,
)


@pytest.fixture(scope="module")
def tiny_hf_whisper():
    cfg = transformers.WhisperConfig(
        vocab_size=51200, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=2, decoder_attention_heads=2,
        encoder_ffn_dim=64, decoder_ffn_dim=64, num_mel_bins=16,
        max_source_positions=64, max_target_positions=32,
    )
    torch.manual_seed(0)
    hf = transformers.WhisperForConditionalGeneration(cfg)
    hf.eval()
    our_cfg = config_from_hf(cfg)
    our_cfg["dtype"] = "float32"
    bundle = models.build_model("whisper", our_cfg)
    params = convert_state_dict(hf.state_dict(), our_cfg)
    params = jax.tree.map(lambda x: np.asarray(x, np.float32), params)
    return hf, bundle, params


def test_encoder_matches_hf(tiny_hf_whisper):
    hf, bundle, params = tiny_hf_whisper
    mel = np.random.RandomState(0).rand(1, 16, 128).astype(np.float32)
    ours = bundle.encode(params, mel)
    with torch.no_grad():
        theirs = hf.model.encoder(torch.from_numpy(mel)).last_hidden_state
    np.testing.assert_allclose(
        np.asarray(ours), theirs.numpy(), rtol=2e-4, atol=2e-4
    )


def test_decoder_forward_matches_hf(tiny_hf_whisper):
    hf, bundle, params = tiny_hf_whisper
    mel = np.random.RandomState(1).rand(1, 16, 128).astype(np.float32)
    tokens = np.array([[50258, 50359, 50363, 11, 23, 42]], np.int64)
    enc = bundle.encode(params, mel)
    ours = bundle.decoder_forward(params, tokens.astype(np.int32), enc)
    with torch.no_grad():
        theirs = hf(
            input_features=torch.from_numpy(mel),
            decoder_input_ids=torch.from_numpy(tokens),
        ).logits
    np.testing.assert_allclose(
        np.asarray(ours), theirs.numpy(), rtol=2e-3, atol=2e-3
    )


def test_cached_decode_matches_forward(tiny_hf_whisper):
    """The serving decode path (self-KV cache + precomputed cross KV) must
    match the teacher-forced forward exactly."""
    hf, bundle, params = tiny_hf_whisper
    mel = np.random.RandomState(2).rand(1, 16, 128).astype(np.float32)
    tokens = np.array([[50258, 50359, 50363, 7, 9]], np.int32)
    enc = bundle.encode(params, mel)
    full = bundle.decoder_forward(params, tokens, enc)      # [1, S, V]

    cache = bundle.init_cache(params, enc, max_len=16)
    step_logits = []
    for i in range(tokens.shape[1]):
        logits, cache = bundle.decode(params, tokens[:, i], cache)
        step_logits.append(np.asarray(logits))
    np.testing.assert_allclose(
        np.stack(step_logits, axis=1), np.asarray(full), rtol=2e-4, atol=2e-4
    )


def test_log_mel_matches_feature_extractor():
    fe = transformers.WhisperFeatureExtractor(
        feature_size=16, sampling_rate=16000, hop_length=160, chunk_length=2, n_fft=400
    )
    rng = np.random.RandomState(3)
    pcm = (rng.rand(20000).astype(np.float32) - 0.5) * 0.2
    theirs = fe(pcm, sampling_rate=16000, return_tensors="np").input_features[0]
    ours = log_mel_spectrogram(
        pcm, np.asarray(fe.mel_filters), n_fft=400, hop_length=160,
        n_samples=fe.n_samples,
    )
    assert ours.shape == theirs.shape
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)


def test_mel_filter_bank_fallback_close_to_hf():
    ours = mel_filter_bank(16, 400, 16000)
    from transformers.audio_utils import mel_filter_bank as hf_bank

    theirs = np.asarray(
        hf_bank(
            num_frequency_bins=201, num_mel_filters=16, min_frequency=0.0,
            max_frequency=8000.0, sampling_rate=16000, norm="slaney",
            mel_scale="slaney",
        )
    )
    assert ours.shape == theirs.shape
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-5)


def test_decode_wav_roundtrip():
    import io
    import wave

    rate = 8000
    t = np.linspace(0, 1, rate, endpoint=False)
    sig = (0.5 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(2)
        w.setsampwidth(2)
        w.setframerate(rate)
        stereo = np.stack([sig, sig], axis=1)
        w.writeframes((stereo * 32767).astype(np.int16).tobytes())
    pcm = decode_wav(buf.getvalue(), target_rate=16000)
    assert pcm.shape[0] == 16000  # resampled 1s
    assert np.max(np.abs(pcm)) == pytest.approx(0.5, rel=0.05)


@pytest.fixture(scope="module")
def audio_served(tmp_path_factory):
    """Whisper-test endpoint (random weights) served through the router."""
    import os

    from clearml_serving_tpu.engines.jax_engine import save_bundle
    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    root = tmp_path_factory.mktemp("audio_state")
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    bundle = models.build_model("whisper", {"preset": "whisper-test"})
    params = bundle.init(jax.random.PRNGKey(0))
    cfg = dict(bundle.config)
    cfg.update(
        transcribe_prompt_ids=[300, 301, 302],
        translate_prompt_ids=[300, 303, 302],
        eos_token_id=399,
        sampling_rate=16000,
        chunk_length=2,  # 2s windows keep the test tiny
    )
    bdir = tmp_path_factory.mktemp("audio_bundle") / "whisper"
    save_bundle(bdir, "whisper", cfg, params)
    mrp = ModelRequestProcessor(state_root=str(root), force_create=True, name="audio")
    rec = mrp.registry.register("whisper-test", path=bdir, framework="jax")
    mrp.add_endpoint(
        ModelEndpoint(engine_type="llm", serving_url="tiny_whisper", model_id=rec.id)
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def _tone_wav(seconds=1.0, rate=16000) -> bytes:
    import io
    import wave

    t = np.linspace(0, seconds, int(rate * seconds), endpoint=False)
    sig = (0.3 * np.sin(2 * np.pi * 300 * t)).astype(np.float32)
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes((sig * 32767).astype(np.int16).tobytes())
    return buf.getvalue()


def test_audio_transcription_route_multipart(audio_served):
    import asyncio

    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from clearml_serving_tpu.serving.main import build_app

    async def fn():
        client = TestClient(TestServer(build_app(audio_served)))
        await client.start_server()
        try:
            form = aiohttp.FormData()
            form.add_field("file", _tone_wav(), filename="a.wav",
                           content_type="audio/wav")
            form.add_field("model", "tiny_whisper")
            r = await client.post("/serve/openai/v1/audio/transcriptions", data=form)
            assert r.status == 200, await r.text()
            out = await r.json()
            # translation task uses its own prompt ids
            form2 = aiohttp.FormData()
            form2.add_field("file", _tone_wav(0.5), filename="b.wav",
                            content_type="audio/wav")
            form2.add_field("model", "tiny_whisper")
            form2.add_field("response_format", "verbose_json")
            r2 = await client.post("/serve/openai/v1/audio/translations", data=form2)
            assert r2.status == 200, await r2.text()
            return out, await r2.json()
        finally:
            await client.close()

    out, out2 = asyncio.run(fn())
    assert "text" in out and isinstance(out["text"], str)
    assert out2["task"] == "translate"
    assert out2["duration"] == pytest.approx(0.5, abs=0.01)


def test_audio_transcription_json_base64(audio_served):
    import asyncio
    import base64

    async def fn():
        return await audio_served.process_request(
            "tiny_whisper",
            None,
            {"file": base64.b64encode(_tone_wav(0.3)).decode()},
            serve_type="v1/audio/transcriptions",
        )

    out = asyncio.run(fn())
    assert "text" in out


def test_audio_route_gated_on_decoder_endpoint(tmp_path):
    """v1/audio/* on a text-LLM endpoint must 422 cleanly."""
    import asyncio
    import os

    from clearml_serving_tpu.engines.base import EndpointModelError
    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    os.environ["TPUSERVE_STATE_ROOT"] = str(tmp_path)
    mrp = ModelRequestProcessor(state_root=str(tmp_path), force_create=True, name="gate")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="text_llm",
            auxiliary_cfg={"engine": {"preset": "llama-tiny",
                                      "config": {"dtype": "float32"}}},
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    with pytest.raises(EndpointModelError, match="does not support"):
        asyncio.run(
            mrp.process_request(
                "text_llm", None, {"file": "x"},
                serve_type="v1/audio/transcriptions",
            )
        )


def test_decode_float32_wav():
    """IEEE-float WAVs (soundfile's default) must decode via the RIFF
    fallback — stdlib wave rejects format 3 (review r2 finding)."""
    import struct

    rate = 16000
    sig = (0.25 * np.sin(2 * np.pi * 220 * np.linspace(0, 0.5, rate // 2))).astype(
        np.float32
    )
    payload = sig.tobytes()
    fmt = struct.pack("<HHIIHH", 3, 1, rate, rate * 4, 4, 32)
    data = (
        b"RIFF" + struct.pack("<I", 4 + 8 + len(fmt) + 8 + len(payload)) + b"WAVE"
        + b"fmt " + struct.pack("<I", len(fmt)) + fmt
        + b"data" + struct.pack("<I", len(payload)) + payload
    )
    pcm = decode_wav(data, target_rate=16000)
    assert pcm.shape[0] == rate // 2
    np.testing.assert_allclose(pcm, sig, rtol=1e-6)


def test_audio_text_response_and_bad_multipart(audio_served):
    import asyncio

    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from clearml_serving_tpu.serving.main import build_app

    async def fn():
        client = TestClient(TestServer(build_app(audio_served)))
        await client.start_server()
        try:
            form = aiohttp.FormData()
            form.add_field("file", _tone_wav(0.3), filename="a.wav",
                           content_type="audio/wav")
            form.add_field("model", "tiny_whisper")
            form.add_field("response_format", "text")
            r = await client.post("/serve/openai/v1/audio/transcriptions", data=form)
            assert r.status == 200
            # OpenAI parity: raw text/plain body, not a JSON-quoted string
            assert r.headers["Content-Type"].startswith("text/plain")
            text = await r.text()
            assert not text.startswith('"')

            # malformed multipart must 422 with the JSON error contract
            r2 = await client.post(
                "/serve/openai/v1/audio/transcriptions",
                data=b"garbage",
                headers={"Content-Type": "multipart/form-data"},  # no boundary
            )
            assert r2.status == 422, await r2.text()
            body = await r2.json()
            assert "detail" in body
            return True
        finally:
            await client.close()

    assert asyncio.run(fn())


def test_audio_batched_matches_sequential(audio_served):
    """Micro-batched transcription must produce the same tokens as the
    per-utterance path, for concurrent requests of different audio."""
    import asyncio

    processor = audio_served._get_processor("tiny_whisper")
    core = processor.audio

    rng = np.random.RandomState(7)
    pcms = [
        (rng.rand(16000) - 0.5).astype(np.float32) * 0.4 for _ in range(3)
    ]
    sequential = [core.transcribe_ids(p, "transcribe") for p in pcms]

    async def run():
        return await asyncio.gather(
            *[core.transcribe_ids_async(p, "transcribe") for p in pcms]
        )

    batched = asyncio.run(run())
    assert batched == sequential


# -- timestamp-conditioned decoding (verbose_json segments) -----------------

_TS_CFG = dict(
    preset="whisper-test",
    transcribe_prompt_ids=[300, 301, 302, 349],   # ends with <|notimestamps|>
    translate_prompt_ids=[300, 303, 302, 349],
    eos_token_id=340,
    notimestamps_token_id=349,
    timestamp_begin=350,                           # ids 350..399 = 0..0.98s
    time_precision=0.02,
    sampling_rate=16000,
    chunk_length=1,                                # 1s windows for CI speed
)


@pytest.fixture(scope="module")
def ts_audio_core():
    from clearml_serving_tpu.llm.audio import AudioCore

    bundle = models.build_model("whisper", dict(_TS_CFG))
    params = bundle.init(jax.random.PRNGKey(3))
    return AudioCore(bundle, params, decode_steps=4, max_new_tokens=12)


def test_timestamp_rules_wellformed(ts_audio_core):
    """In-graph decoding rules guarantee well-formed marker structure even
    with random weights: first token is a timestamp, timestamps never
    decrease, and a completed pair is never followed by a third marker."""
    core = ts_audio_core
    rng = np.random.RandomState(0)
    pcm = (0.1 * rng.randn(16000)).astype(np.float32)
    prompt = core.prompt_ids("transcribe", timestamps=True)
    assert 349 not in prompt  # <|notimestamps|> stripped
    outs = core._transcribe_batch_ts([pcm, pcm], prompt)
    assert len(outs) == 2
    for ids in outs:
        assert ids, "timestamp decode emitted nothing"
        assert ids[0] >= 350, "first sampled token must be a timestamp"
        # the initial marker completes a pair by the len<2 convention (HF
        # WhisperTimeStampLogitsProcessor): TEXT must follow, not a marker
        if len(ids) > 1:
            assert ids[1] < 350, "second token must be text: {}".format(ids)
        last_ts = ids[0]
        run = 1  # ids[0] is a marker
        for t in ids[1:]:
            if t >= 350:
                run += 1
                assert run <= 2, "three timestamps in a row: {}".format(ids)
                if last_ts is not None:
                    assert t >= last_ts, "timestamps decreased: {}".format(ids)
                last_ts = t
            else:
                run = 0


def test_parse_segments(ts_audio_core):
    core = ts_audio_core
    # window 0: <|0.1|> text <|0.3|><|0.3|> text <|0.5|>; window 1: tail
    w0 = [355, 341, 342, 365, 365, 343, 375]
    w1 = [352, 344, 345]  # unterminated: closes at min(duration, window end)
    segs = core.parse_segments([w0, w1], duration=1.7)
    assert [s["id"] for s in segs] == [0, 1, 2]
    assert segs[0]["start"] == pytest.approx(0.1) and segs[0]["end"] == pytest.approx(0.3)
    assert segs[0]["tokens"] == [341, 342]
    assert segs[1]["start"] == pytest.approx(0.3) and segs[1]["end"] == pytest.approx(0.5)
    assert segs[1]["tokens"] == [343]
    # window 1 offsets by the 1s window length; tail closes at duration=1.7
    assert segs[2]["start"] == pytest.approx(1.04)
    assert segs[2]["end"] == pytest.approx(1.7)
    assert segs[2]["tokens"] == [344, 345]


@pytest.fixture(scope="module")
def ts_audio_served(tmp_path_factory):
    """Timestamp-capable whisper endpoint served through the router."""
    import os

    from clearml_serving_tpu.engines.jax_engine import save_bundle
    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    root = tmp_path_factory.mktemp("ts_audio_state")
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    bundle = models.build_model("whisper", dict(_TS_CFG))
    params = bundle.init(jax.random.PRNGKey(3))
    bdir = tmp_path_factory.mktemp("ts_audio_bundle") / "whisper"
    save_bundle(bdir, "whisper", dict(bundle.config), params)
    mrp = ModelRequestProcessor(state_root=str(root), force_create=True, name="tsaudio")
    rec = mrp.registry.register("whisper-ts", path=bdir, framework="jax")
    mrp.add_endpoint(
        ModelEndpoint(engine_type="llm", serving_url="ts_whisper", model_id=rec.id)
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def test_verbose_json_segments_route(ts_audio_served):
    import asyncio
    import base64

    async def fn():
        return await ts_audio_served.process_request(
            "ts_whisper",
            None,
            {
                "file": base64.b64encode(_tone_wav(0.6)).decode(),
                "response_format": "verbose_json",
            },
            serve_type="v1/audio/transcriptions",
        )

    out = asyncio.run(fn())
    assert out["duration"] == pytest.approx(0.6, abs=0.01)
    assert "segments" in out and len(out["segments"]) >= 1
    for seg in out["segments"]:
        assert set(seg) >= {"id", "seek", "start", "end", "tokens", "text"}
        # boundaries clamp to the real audio duration, not the padded window
        assert 0.0 <= seg["start"] <= seg["end"] <= out["duration"] + 1e-6
        assert all(t < 350 for t in seg["tokens"]) or seg["tokens"] == []
    # the top-level text contains no marker tokens (they decode per segment)
    assert isinstance(out["text"], str)


def test_words_from_segments():
    from clearml_serving_tpu.llm.audio import AudioCore

    segs = [
        {"text": "ab cdef", "start": 1.0, "end": 4.0},
        {"text": "", "start": 4.0, "end": 5.0},       # empty: no words
        {"text": "x", "start": 5.0, "end": 5.5},
    ]
    words = AudioCore.words_from_segments(segs)
    assert [w["word"] for w in words] == ["ab", "cdef", "x"]
    # proportional by characters: "ab" gets 2/6 of 3s, "cdef" 4/6
    assert words[0]["start"] == pytest.approx(1.0)
    assert words[0]["end"] == pytest.approx(2.0)
    assert words[1]["start"] == pytest.approx(2.0)
    assert words[1]["end"] == pytest.approx(4.0)
    assert words[2]["start"] == pytest.approx(5.0)
    assert words[2]["end"] == pytest.approx(5.5)
    # monotone, within-span
    for w in words:
        assert w["start"] <= w["end"]


def test_word_granularity_route(ts_audio_served):
    import asyncio
    import base64

    async def fn():
        return await ts_audio_served.process_request(
            "ts_whisper",
            None,
            {
                "file": base64.b64encode(_tone_wav(0.6)).decode(),
                "response_format": "verbose_json",
                "timestamp_granularities": ["word", "segment"],
            },
            serve_type="v1/audio/transcriptions",
        )

    out = asyncio.run(fn())
    assert "segments" in out and "words" in out
    for w in out["words"]:
        assert set(w) == {"word", "start", "end"}
        assert 0.0 <= w["start"] <= w["end"] <= out["duration"] + 1e-6

    # word-only granularity omits segments (OpenAI shape)
    async def fn2():
        return await ts_audio_served.process_request(
            "ts_whisper",
            None,
            {
                "file": base64.b64encode(_tone_wav(0.6)).decode(),
                "response_format": "verbose_json",
                "timestamp_granularities": ["word"],
            },
            serve_type="v1/audio/transcriptions",
        )

    out2 = asyncio.run(fn2())
    assert "words" in out2 and "segments" not in out2


# -- word timestamps: cross-attention DTW -----------------------------------

def test_dtw_path_diagonal_and_monotone():
    from clearml_serving_tpu.llm.audio import _dtw_path

    # strong diagonal: the path must follow it
    n, m = 4, 8
    cost = np.ones((n, m))
    for i in range(n):
        cost[i, 2 * i : 2 * i + 2] = 0.0
    ti, fi = _dtw_path(cost)
    assert ti[0] == 0 and fi[0] == 0
    assert ti[-1] == n - 1 and fi[-1] == m - 1
    # monotone non-decreasing, single steps
    assert (np.diff(ti) >= 0).all() and (np.diff(fi) >= 0).all()
    assert ((np.diff(ti) + np.diff(fi)) >= 1).all()
    # each token's run sits on its low-cost band
    for k in range(n):
        frames = fi[ti == k]
        assert cost[k, frames].mean() <= 0.5


def test_median_filter_time():
    from clearml_serving_tpu.llm.audio import _median_filter_time

    x = np.zeros((2, 3, 9))
    x[..., 4] = 100.0  # lone spike is removed by a width-7 median
    out = _median_filter_time(x, 7)
    assert out.shape == x.shape
    assert np.abs(out).max() == 0.0
    ramp = np.arange(9, dtype=float)[None, None]
    out = _median_filter_time(ramp, 7)
    assert out[0, 0, 4] == pytest.approx(4.0)  # interior preserved


class _StubTok:
    """Maps text ids to letters; id 341 decodes with a LEADING SPACE so the
    word grouper splits there."""

    def decode(self, ids):
        out = []
        for t in ids:
            if t == 341:
                out.append(" b")
            else:
                out.append(chr(ord("a") + (t - 330) % 26))
        return "".join(out)


def test_words_dtw_monotone_and_grouped(ts_audio_core):
    core = ts_audio_core
    rng = np.random.RandomState(0)
    pcm = (0.1 * rng.randn(16000)).astype(np.float32)  # one 1s window
    # window ids: <|t0.1|> text text text <|t0.4|>
    windows = [[355, 334, 341, 335, 370]]
    words = core.words_dtw(pcm, windows, _StubTok())
    assert words is not None and len(words) == 2
    # grouping: "e" then " bf" -> words "e", "bf"
    assert [w["word"] for w in words] == ["e", "bf"]
    dur = len(pcm) / core.sampling_rate
    prev_end = 0.0
    for w in words:
        assert 0.0 <= w["start"] <= w["end"] <= dur + 1e-6
        assert w["start"] >= prev_end - 0.3  # near-monotone across words
        prev_end = w["end"]


def test_verbose_json_word_granularity_route(ts_audio_served):
    import asyncio
    import base64

    async def fn():
        return await ts_audio_served.process_request(
            "ts_whisper",
            None,
            {
                "file": base64.b64encode(_tone_wav(0.6)).decode(),
                "response_format": "verbose_json",
                "timestamp_granularities": ["word", "segment"],
            },
            serve_type="v1/audio/transcriptions",
        )

    out = asyncio.run(fn())
    assert "segments" in out and "words" in out
    for w in out["words"]:
        assert set(w) == {"word", "start", "end"}
        assert 0.0 <= w["start"] <= w["end"] <= out["duration"] + 1e-6
        assert w["word"].strip() == w["word"] != ""


class _ByteStubTok:
    """Byte-level BPE stand-in: 'ü' (0xC3 0xBC) split across two tokens."""

    TABLE = {334: b"\xc3", 335: b"\xbc", 336: b"ber", 337: b" x"}

    def decode(self, ids):
        return b"".join(
            self.TABLE.get(t, b"") for t in ids
        ).decode("utf-8", errors="replace")


def test_words_dtw_utf8_safe_units(ts_audio_core):
    """Tokens splitting a multi-byte codepoint must accumulate until they
    decode cleanly — never emit U+FFFD mojibake (r5 code review)."""
    core = ts_audio_core
    rng = np.random.RandomState(0)
    pcm = (0.1 * rng.randn(16000)).astype(np.float32)
    # <|t|> 0xC3 0xBC "ber" " x" <|t|>
    windows = [[355, 334, 335, 336, 337, 370]]
    words = core.words_dtw(pcm, windows, _ByteStubTok())
    assert [w["word"] for w in words] == ["über", "x"]
    assert all("�" not in w["word"] for w in words)


def test_words_dtw_breaks_at_segment_boundaries(ts_audio_core):
    """Timestamp markers break words even without whitespace — bounds word
    length for unspaced scripts (r5 code review)."""
    core = ts_audio_core
    rng = np.random.RandomState(0)
    pcm = (0.1 * rng.randn(16000)).astype(np.float32)
    # two segments, no whitespace anywhere: <|t|> ber <|t|><|t|> ber <|t|>
    windows = [[355, 336, 365, 365, 336, 375]]
    words = core.words_dtw(pcm, windows, _ByteStubTok())
    assert [w["word"] for w in words] == ["ber", "ber"]
    assert words[0]["end"] <= words[1]["start"] + 0.3


def test_cross_attention_alignment_matches_hf(tiny_hf_whisper):
    """The DTW timing source — per-head cross-attention probabilities from
    the teacher-forced pass — must match transformers' cross_attentions
    exactly (same checkpoint, same tokens). This pins the word-timestamp
    pipeline's input to the reference implementation."""
    hf, bundle, params = tiny_hf_whisper
    mel = np.random.RandomState(2).rand(1, 16, 128).astype(np.float32)
    tokens = np.array([[50258, 50359, 50363, 11, 23, 42]], np.int64)
    enc = bundle.encode(params, mel)
    heads = ((0, 0), (0, 1), (1, 0), (1, 1))
    ours = np.asarray(bundle.cross_attention_alignment(
        params, tokens.astype(np.int32), enc, heads
    ))                                                 # [N, 1, S, T]
    # SDPA attention returns no attention maps; rebuild eager with the
    # same weights
    eager = transformers.WhisperForConditionalGeneration._from_config(
        hf.config, attn_implementation="eager"
    )
    eager.load_state_dict(hf.state_dict())
    eager.eval()
    with torch.no_grad():
        out = eager(
            input_features=torch.from_numpy(mel),
            decoder_input_ids=torch.from_numpy(tokens),
            output_attentions=True,
        )
    for n, (l, h) in enumerate(heads):
        theirs = out.cross_attentions[l][0, h].numpy()  # [S, T]
        np.testing.assert_allclose(ours[n, 0], theirs, rtol=2e-3, atol=2e-3)
    # frame masking: probs beyond n_frames are exactly zero and rows
    # renormalize over the kept frames
    masked = np.asarray(bundle.cross_attention_alignment(
        params, tokens.astype(np.int32), enc, heads, n_frames=10
    ))
    assert np.abs(masked[..., 10:]).max() == 0.0
    np.testing.assert_allclose(masked.sum(-1), 1.0, rtol=1e-5)


def test_words_dtw_forced_flush_never_emits_mojibake(ts_audio_core):
    """A unit cut off mid-codepoint by a segment boundary or window end
    drops the incomplete bytes instead of emitting U+FFFD (r5 review)."""
    core = ts_audio_core
    rng = np.random.RandomState(0)
    pcm = (0.1 * rng.randn(16000)).astype(np.float32)
    # segment boundary right after a lone continuation byte; then a clean
    # token; window ends with another dangling partial codepoint
    windows = [[355, 334, 365, 365, 336, 375, 334]]
    words = core.words_dtw(pcm, windows, _ByteStubTok())
    assert [w["word"] for w in words] == ["ber"]
    assert all("�" not in w["word"] for w in words)
